"""Context-manager timing spans + Chrome trace export.

A span times a named region and emits one ``span`` event on exit::

    with obs.span("data_wait", take=4):
        batches = tuple(next(stream) for _ in range(4))

Emitted fields: ``name``, ``dur_s``, ``t`` (wall-clock *start*, so trace
viewers place the interval correctly), ``thread`` (ident), ``parent`` (the
enclosing span's name, tracked per-thread), plus any caller fields. The
duration clock is ``perf_counter`` — monotonic, immune to NTP steps that
would corrupt a wall-clock subtraction mid-run.

Nesting is tracked in a thread-local stack, so producer threads, the train
loop, and an eval pass each get independent, correctly-parented spans with
no cross-thread locking beyond the sink's own line lock.

When no run is active (``events._sink is None``) ``span()`` returns a
shared no-op singleton: one ``None`` check, no clock reads, no allocation
beyond the call itself — the hot dispatch loop pays nothing.
"""

from __future__ import annotations

import threading
import time

from featurenet_tpu.obs import events as _events
from featurenet_tpu.obs import windows as _windows

_tls = threading.local()


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "fields", "_t0", "_wall0")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.name)
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        stack = _tls.stack
        stack.pop()
        _events.emit(
            "span",
            t=self._wall0,
            name=self.name,
            dur_s=dur,
            thread=threading.get_ident(),
            parent=stack[-1] if stack else None,
            **self.fields,
        )
        # Live-SLO feed: the spans that are window metrics (data_wait,
        # infer_batch) land in the rolling aggregator too — the duration
        # is already in hand, so the live view costs no extra clock read.
        _windows.observe_span(self.name, dur, self.fields)
        return False


def span(name: str, **fields):
    """A timing span, or the shared no-op when no run is active."""
    if _events._sink is None:
        return _NULL
    return _Span(name, fields)


# --- Chrome trace export -----------------------------------------------------

_SPAN_META = ("t", "ev", "name", "dur_s", "thread", "parent", "pid",
              "process_index")


def chrome_trace(events: list[dict]) -> dict:
    """Fold ``span`` events into Chrome tracing's JSON object format
    (load via chrome://tracing or https://ui.perfetto.dev). Complete
    ("ph":"X") events, microsecond timestamps rebased to the earliest
    event so the viewer opens at t=0; tid is the thread ident.

    Tracks: every distinct ``(process_index, os pid)`` writer gets its own
    synthetic trace pid — OS pids from different hosts can collide, so the
    raw pid cannot be the track key in a merged multi-host log — with a
    ``process_name`` metadata record naming the host and real pid, and
    ``process_sort_index`` ordering tracks by host.

    ``window_summary`` events export as counter ("ph":"C") tracks — one
    per metric — so the rolling p50/p95/p99 render as stepped series
    above the span lanes they summarize (the ``mfu`` window rides this
    path: an MFU counter track for free). ``device_memory`` events (the
    obs.perf heartbeat-cadence poller) export as one counter track per
    device — the HBM watermark next to the spans that caused it.

    Sampled request traces (obs.tracing) export as ASYNC events — one
    ``b``/``e`` pair per trace id spanning admit→done (or →reject), with
    an instant at the dispatch point — plus a flow arrow ("ph":"s"/"f")
    from the admit to the dispatch, so a batch's N fanned-in requests
    are visually tied to the ``serve_dispatch`` span carrying the same
    ``batch_seq``."""
    spans = [e for e in events if e.get("ev") == "span" and "dur_s" in e]
    windows = [e for e in events
               if e.get("ev") == "window_summary" and "metric" in e]
    mem = [e for e in events
           if e.get("ev") == "device_memory" and "bytes_in_use" in e]
    reqs = [e for e in events
            if e.get("ev") in ("request_admit", "request_dispatch",
                               "request_done", "request_reject")
            and "trace" in e]
    if not spans and not windows and not mem and not reqs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e["t"] for e in spans + windows + mem + reqs)
    track_ids: dict[tuple, int] = {}

    def track(e: dict) -> int:
        key = (e.get("process_index", 0) or 0, e.get("pid", 0))
        if key not in track_ids:
            track_ids[key] = len(track_ids)
        return track_ids[key]

    out = []
    for e in spans:
        out.append({
            "name": e.get("name", "?"),
            "ph": "X",
            "ts": (e["t"] - t0) * 1e6,
            "dur": e["dur_s"] * 1e6,
            "pid": track(e),
            "tid": e.get("thread", 0),
            "args": {k: v for k, v in e.items() if k not in _SPAN_META},
        })
    for e in windows:
        out.append({
            "name": f"window {e['metric']}",
            "ph": "C",
            "ts": (e["t"] - t0) * 1e6,
            "pid": track(e),
            "args": {
                k: e[k] for k in ("p50", "p95", "p99")
                if isinstance(e.get(k), (int, float))
            },
        })
    for e in mem:
        out.append({
            "name": f"device {e.get('device', 0)} memory",
            "ph": "C",
            "ts": (e["t"] - t0) * 1e6,
            "pid": track(e),
            "args": {
                k: e[k] for k in ("bytes_in_use", "peak_bytes_in_use")
                if isinstance(e.get(k), (int, float))
            },
        })
    # Async request lanes + flow arrows (one lane per sampled trace id;
    # the b/e pair spans the request's whole server-side life, the flow
    # links its admit point into the dispatch that served it).
    _ASYNC_PH = {"request_admit": "b", "request_done": "e",
                 "request_reject": "e"}
    by_trace: dict[str, list[dict]] = {}
    for e in reqs:
        by_trace.setdefault(str(e["trace"]), []).append(e)
    for trace, evs in sorted(by_trace.items()):
        evs.sort(key=lambda e: e["t"])
        for e in evs:
            ph = _ASYNC_PH.get(e["ev"], "n")
            out.append({
                "name": "request",
                "cat": "request",
                "ph": ph,
                "id": trace,
                "ts": (e["t"] - t0) * 1e6,
                "pid": track(e),
                "tid": e.get("thread", 0),
                "args": {
                    k: e[k] for k in ("batch_seq", "bucket", "pad",
                                      "queue_wait_ms", "dispatch_ms",
                                      "total_ms", "outcome")
                    if e.get(k) is not None
                },
            })
        admit = next((e for e in evs if e["ev"] == "request_admit"), None)
        disp = next((e for e in evs if e["ev"] == "request_dispatch"),
                    None)
        if admit is not None and disp is not None:
            for e, ph in ((admit, "s"), (disp, "f")):
                out.append({
                    "name": "request-flow",
                    "cat": "request",
                    "ph": ph,
                    "id": trace,
                    "ts": (e["t"] - t0) * 1e6,
                    "pid": track(e),
                    "tid": e.get("thread", 0),
                    **({"bp": "e"} if ph == "f" else {}),
                })
    meta = []
    for (host, ospid), tpid in sorted(track_ids.items(), key=lambda kv: kv[1]):
        meta.append({
            "name": "process_name", "ph": "M", "pid": tpid,
            "args": {"name": f"host {host} (pid {ospid})"},
        })
        meta.append({
            "name": "process_sort_index", "ph": "M", "pid": tpid,
            "args": {"sort_index": host},
        })
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}
