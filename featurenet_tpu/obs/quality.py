"""Model-quality telemetry: confidence statistics and prediction-mix
drift over the serving path.

Sixteen PRs of observability watch the *system* — step time, queue
depth, connection churn. This module watches the *model*: per-request
top-1 confidence, the top1−top2 margin, and softmax entropy flow into
the rolling windows (``confidence``, ``confidence_margin``,
``prediction_entropy`` in ``WINDOW_METRICS``), and a rolling
predicted-class histogram is scored against a pinned baseline class
distribution with a total-variation **drift score**
(``quality_drift_score``). Because all four ride the ordinary window
machinery, they reach the /metrics exporters, the fleet scraper, the
tsdb, burn-rate SLOs, ``cli dash``, and ``cli report`` with zero new
plumbing — and alert rules like ``confidence_p50<0.5`` or
``quality_drift_score_p50>0.25`` parse, fire, and resolve through the
existing hysteresis engine.

The baseline is a JSON artifact (``quality_baseline.json``) written by
``cli pin-quality`` from an eval run over the synthetic corpus: the
class mix the model is *expected* to emit on healthy traffic. Drift is
the total-variation distance between that distribution and the rolling
window of live predictions — 0 for an identical mix, 1 for disjoint
support. A skewed input mix (or a quietly broken model collapsing onto
one class) pushes the score up; the mix returning to normal brings it
back down, which is exactly the fire→resolve pair the alert engine
renders.

The tracker is fed *floats*, never arrays: the serving layer reduces
each probability row to (label, confidence, margin, entropy) at the
batcher's result hook, so this module — like the rest of the obs
package — stays stdlib-only and import-safe everywhere.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
from typing import Optional, Sequence

from featurenet_tpu.obs import events as _events
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.obs.alerts import AlertRule

BASELINE_FILENAME = "quality_baseline.json"

# Rolling histogram span: wide enough that one weird batch doesn't spike
# the score, short enough that a real mix shift (or its recovery) clears
# the window within a few emission cycles.
DEFAULT_WINDOW = 512

# Emit one `quality_drift` event per this many observed predictions —
# the report's quality section folds these; per-request events would
# dwarf the stream they ride in.
DEFAULT_EMIT_EVERY = 64

# Default alert thresholds (`quality_rules`): a median top-1 confidence
# under the floor is a model losing its grip; a median drift score over
# the ceiling is a prediction mix that no longer resembles the pinned
# baseline.
DEFAULT_CONFIDENCE_FLOOR = 0.5
DEFAULT_DRIFT_CEILING = 0.25


def confidence_stats(probs: Sequence[float]) -> tuple[float, float, float]:
    """(top-1 confidence, top1−top2 margin, entropy in nats) of one
    probability row. Pure stdlib math over floats — the caller hands us
    a plain sequence, not an array."""
    if not probs:
        return 0.0, 0.0, 0.0
    top1 = top2 = 0.0
    ent = 0.0
    for p in probs:
        p = float(p)
        if p > top1:
            top1, top2 = p, top1
        elif p > top2:
            top2 = p
        if p > 0.0:
            ent -= p * math.log(p)
    return top1, top1 - top2, ent


def drift_score(counts: Sequence[float],
                baseline: Sequence[float]) -> float:
    """Total-variation distance between a predicted-class count vector
    and a baseline distribution: ``0.5 * sum |p_i - q_i|`` after
    normalizing the counts. 0 = identical mix, 1 = disjoint support.
    Classes beyond either vector's length count as probability zero, so
    a baseline pinned on an older class universe still scores."""
    n = float(sum(counts))
    if n <= 0.0:
        return 0.0
    width = max(len(counts), len(baseline))
    tv = 0.0
    for i in range(width):
        p = float(counts[i]) / n if i < len(counts) else 0.0
        q = float(baseline[i]) if i < len(baseline) else 0.0
        tv += abs(p - q)
    return 0.5 * tv


def save_baseline(path: str, counts: Sequence[int], *,
                  class_names: Optional[Sequence[str]] = None,
                  source: Optional[dict] = None) -> dict:
    """Normalize a class-count vector and pin it as the baseline
    artifact (atomic tmp+replace, like run.json). Returns the record
    written. Refuses an empty count vector — a baseline that matches
    nothing is an SLO that tests nothing."""
    total = int(sum(counts))
    if total <= 0:
        raise ValueError(
            "quality baseline needs at least one prediction to pin"
        )
    rec = {
        "version": 1,
        "n": total,
        "dist": [round(int(c) / total, 6) for c in counts],
    }
    if class_names:
        rec["class_names"] = list(class_names)
    if source:
        rec["source"] = source
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(rec, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return rec


def load_baseline(path: str) -> dict:
    """Read and validate a pinned baseline. Raises ValueError on a
    malformed artifact — the same config-time refusal convention as the
    alert-rule parser: a baseline that silently fails to load is drift
    monitoring that silently never runs."""
    try:
        with open(path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable quality baseline {path!r}: {e}") \
            from None
    dist = rec.get("dist")
    if not isinstance(dist, list) or not dist or \
            not all(isinstance(v, (int, float)) and v >= 0 for v in dist):
        raise ValueError(
            f"quality baseline {path!r} has no usable 'dist' vector"
        )
    total = float(sum(dist))
    if not 0.99 <= total <= 1.01:
        raise ValueError(
            f"quality baseline {path!r} dist sums to {total:.4f}, "
            "expected ~1.0"
        )
    return rec


def baseline_path(run_dir: str) -> str:
    return os.path.join(run_dir, BASELINE_FILENAME)


def quality_rules(
    confidence_floor: float = DEFAULT_CONFIDENCE_FLOOR,
    drift_ceiling: float = DEFAULT_DRIFT_CEILING,
    *,
    with_drift: bool = True,
) -> tuple[AlertRule, ...]:
    """The quality plane's alert pair: confidence collapse (median top-1
    under the floor) and, when a baseline is pinned, prediction-mix
    drift (median TV score over the ceiling). Both are ordinary window
    rules — `obs.alerts.is_serving_metric` does not match them, so a
    firing quality alert never fails a serving drain; it pages, it does
    not take the service down."""
    rules = [AlertRule("confidence_p50", "<", float(confidence_floor),
                       "warning")]
    if with_drift:
        rules.append(AlertRule("quality_drift_score_p50", ">",
                               float(drift_ceiling), "warning"))
    return tuple(rules)


class QualityTracker:
    """Rolling model-quality state for one serving process.

    ``observe(label, confidence, margin, entropy)`` is called once per
    answered request (from the batcher's single dispatcher thread; the
    lock keeps multi-writer callers safe anyway). It feeds the three
    confidence windows, advances the rolling per-class histogram, and —
    when a baseline distribution is pinned — scores the current window
    against it, feeding ``quality_drift_score`` and emitting a
    ``quality_drift`` event every ``emit_every`` predictions. Everything
    here is telemetry: no exception escapes into the serving path
    because nothing here raises past arithmetic on floats.
    """

    def __init__(self, num_classes: int,
                 baseline: Optional[Sequence[float]] = None,
                 window: int = DEFAULT_WINDOW,
                 emit_every: int = DEFAULT_EMIT_EVERY):
        self.num_classes = int(num_classes)
        self.baseline = list(baseline) if baseline is not None else None
        self.window = max(1, int(window))
        self.emit_every = max(1, int(emit_every))
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque()
        self._counts = [0] * self.num_classes
        self._seen = 0
        self.last_score: Optional[float] = None

    def observe(self, label: int, confidence: float, margin: float,
                entropy: float) -> Optional[float]:
        """Fold one answered request; returns the current drift score
        (None when no baseline is pinned)."""
        _windows.observe("confidence", float(confidence))
        _windows.observe("confidence_margin", float(margin))
        _windows.observe("prediction_entropy", float(entropy))
        with self._lock:
            label = int(label)
            if 0 <= label < self.num_classes:
                self._ring.append(label)
                self._counts[label] += 1
                if len(self._ring) > self.window:
                    self._counts[self._ring.popleft()] -= 1
            self._seen += 1
            if self.baseline is None:
                return None
            score = drift_score(self._counts, self.baseline)
            self.last_score = score
            emit_now = self._seen % self.emit_every == 0
            n = len(self._ring)
            top = max(range(self.num_classes),
                      key=self._counts.__getitem__) if n else None
        _windows.observe("quality_drift_score", score)
        if emit_now:
            _events.emit("quality_drift", score=round(score, 6), n=n,
                         top_class=top)
        return score

    def stats(self) -> dict:
        with self._lock:
            return {
                "seen": self._seen,
                "window_n": len(self._ring),
                "drift_score": self.last_score,
                "baseline": self.baseline is not None,
            }
