"""Declarative SLO alert rules over the rolling-window aggregator.

The windows layer (``obs.windows``) answers "what are the last N steps'
percentiles"; this module answers "is that bad?". A rule is a threshold
over a window-derived metric::

    data_wait_fraction>0.5:warning

evaluated every time the aggregator emits its ``window_summary`` events.
Alerts are HYSTERETIC fire/resolve pairs: a rule crossing into violation
fires ONE ``alert`` event with ``state="fire"`` and then stays silent —
however many emission cycles the violation lasts — until the metric
recovers, which emits the paired ``state="resolve"`` event (``rule`` /
``severity`` / ``value`` / ``threshold`` / ``window`` / ``state``). A
flapping metric produces a fire/resolve pair per flap, never a re-fire
per cycle. Alerts are *never* load-bearing — the engine only ever writes
telemetry, and the sink it writes through already degrades to a no-op on
ENOSPC.

Rule DSL (``Config.alert_rules`` / ``--alert-rules``, comma-separated)::

    metric(>|<)threshold[:severity]

``metric`` is either a derived metric (``DERIVED_METRICS``) or a window
percentile ``<window>_<stat>`` (``data_wait_ms_p99``, ``queue_depth_p50``,
…); severity is one of ``SEVERITIES`` (default ``warning``). A custom
spec *replaces* the defaults — the operator takes full control. A typo'd
metric fails at config time (the same refusal convention as the faults
DSL): a rule that can silently never evaluate is an SLO that tests
nothing.

One rule is cross-host by nature: ``data_wait_spread`` (the max-min
spread of per-host data-wait fractions — free throughput on a lockstep
mesh). No single process can see it, so it carries ``scope="report"``
and is judged where the streams merge: the report/live-tail layer and
the regression gates, not the in-process engine.

Stdlib-only, like the rest of the obs package.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Optional

from featurenet_tpu.obs import events as _events

SEVERITIES = ("info", "warning", "critical")

# The raw rolling windows the aggregator maintains (obs.windows keys its
# ring buffers off this tuple — defined here so the rule parser and the
# aggregator can never disagree on the metric universe).
WINDOW_METRICS = (
    "step_ms",          # per-step loop wall (dispatch + paced readback)
    "data_wait_ms",     # host blocked on the prefetcher, per dispatch group
    "queue_depth",      # prefetch queue depth at each consumer pop
    "heartbeat_age_s",  # inter-beat age at each confirmed progress point
    "serving_ms",       # per-request end-to-end serving latency (the
                        # infer_batch span, and the continuous batcher's
                        # enqueue→response interval per request)
    "queue_wait_ms",    # serving front end: request enqueue→dispatch wait
                        # (admission pressure building before latency blows)
    "connect_ms",       # fleet.pool: TCP connect wall per FRESH channel —
                        # the handshake cost pooling exists to amortize; a
                        # pool that stops reusing shows up here as volume
                        # (count climbing), not just latency
    "mfu",              # per-dispatch model-flops utilization (obs.perf:
                        # compiled flops over wall over the device-kind
                        # peak; no samples on the `unknown` peak tier)
    "achieved_bw_fraction",  # per-dispatch bytes-accessed over wall over
                        # the device-kind peak HBM bandwidth (obs.perf)
    "confidence",       # per-request top-1 softmax probability (model
                        # quality: a collapsing p50 is the model losing
                        # its grip before accuracy can be measured)
    "confidence_margin",  # per-request top1−top2 probability gap — the
                        # escalation signal the adaptive-resolution
                        # cascade reads (near-zero = ambiguous input)
    "prediction_entropy",  # per-request softmax entropy in nats (uniform
                        # over 24 classes ≈ 3.18; near-zero = peaked)
    "quality_drift_score",  # total-variation distance of the rolling
                        # predicted-class histogram vs the pinned
                        # baseline distribution (obs.quality; 0 = same
                        # mix, 1 = disjoint)
)

_WINDOW_STATS = ("p50", "p95", "p99", "max", "mean")

# Metrics computed *across* windows rather than read off one of them.
DERIVED_METRICS = (
    "data_wait_fraction",   # sum(data_wait_ms) / sum(step_ms)
    "step_p99_ratio",       # p99(step_ms) / p50(step_ms) — tail blowup
    "heartbeat_age_s",      # max of the heartbeat window
    "queue_depth",          # p50 of the depth window (starvation reads low)
    "serving_p99_ms",       # p99 of the serving window
    "data_wait_spread",     # cross-host; report-scope only (see module doc)
    "mfu",                  # p50 of the mfu window (regression reads LOW:
                            # rules use `<`, e.g. mfu<0.3:warning)
)

REPORT_SCOPE_METRICS = frozenset({"data_wait_spread"})


@dataclasses.dataclass(frozen=True)
class AlertRule:
    metric: str
    op: str  # ">" (higher is worse) or "<" (lower is worse)
    threshold: float
    severity: str = "warning"

    @property
    def scope(self) -> str:
        return ("report" if self.metric in REPORT_SCOPE_METRICS
                else "process")

    def violated(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" else \
            value < self.threshold


# Sane defaults (the ISSUE's four): a starving device, a blown step-time
# tail, a heartbeat going quiet well before the supervisor's 600 s kill,
# and (report-scope) a fat cross-host data-wait spread.
DEFAULT_RULES = (
    AlertRule("data_wait_fraction", ">", 0.5, "warning"),
    AlertRule("step_p99_ratio", ">", 4.0, "warning"),
    AlertRule("heartbeat_age_s", ">", 60.0, "critical"),
    AlertRule("data_wait_spread", ">", 0.25, "warning"),
)


def known_metrics() -> set[str]:
    out = set(DERIVED_METRICS)
    for m in WINDOW_METRICS:
        out.update(f"{m}_{s}" for s in _WINDOW_STATS)
    return out


def is_serving_metric(metric: str) -> bool:
    """Whether a rule metric reads off the serving-side windows (request
    latency / queue wait). The serving front end's drain gate keys off
    this: an unresolved serving alert at drain time exits nonzero
    (``cli serve --drain`` / ``cli infer``), while a training-side alert
    never fails a serving drain."""
    return metric.startswith(("serving", "queue_wait"))


_RULE_RE = re.compile(
    r"^(?P<metric>[a-z0-9_]+)(?P<op>[<>])(?P<threshold>[0-9.eE+-]+)"
    r"(?::(?P<severity>[a-z]+))?$"
)


def parse_rules(spec: Optional[str]) -> list[AlertRule]:
    """Parse an ``--alert-rules`` spec; ``None``/empty = the default set.
    Validates metric names, operators, thresholds, and severities so a
    typo fails the run at config time, not silently at alert time."""
    if not spec:
        return list(DEFAULT_RULES)
    rules: list[AlertRule] = []
    seen: set[str] = set()
    valid = known_metrics()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _RULE_RE.match(entry)
        if m is None:
            raise ValueError(
                f"malformed alert rule {entry!r}: expected "
                "metric(>|<)threshold[:severity]"
            )
        metric = m.group("metric")
        if metric not in valid:
            raise ValueError(
                f"unknown alert metric {metric!r} in {entry!r}; known: "
                f"{', '.join(sorted(valid))}"
            )
        if metric in seen:
            raise ValueError(f"duplicate alert metric {metric!r} in {spec!r}")
        seen.add(metric)
        try:
            threshold = float(m.group("threshold"))
        except ValueError:
            raise ValueError(
                f"alert threshold in {entry!r} must be a number"
            ) from None
        severity = m.group("severity") or "warning"
        if severity not in SEVERITIES:
            raise ValueError(
                f"unknown alert severity {severity!r} in {entry!r}; "
                f"one of {', '.join(SEVERITIES)}"
            )
        rules.append(AlertRule(metric, m.group("op"), threshold, severity))
    if not rules:
        raise ValueError(f"empty alert-rules spec {spec!r}")
    return rules


# Alert-timeline mirror: when a time-series store is attached
# (``set_store`` — the fleet CLI wires the scraper's store), every
# fire/resolve transition ALSO lands as an ``alerts_active{rule}`` 0/1
# sample, so dash and the report can render alert timelines from the
# store alone, long after the emitting process exited. The store's
# append already absorbs every failure (go-dark, drops counted), so the
# mirror inherits the never-load-bearing contract for free.
_store = None


def set_store(store) -> None:
    """Attach (or, with None, detach) the store that mirrors alert
    transitions. One process, one store — the same discipline as the
    event sink."""
    global _store
    _store = store


def fire(rule: AlertRule, value: float, window: int,
         state: str = "fire") -> None:
    """One structured ``alert`` event — ``state="fire"`` when the rule
    crosses into violation, ``state="resolve"`` when it recovers (the
    hysteresis pair; the aggregator tracks which transition this is).
    ``window`` is the aggregator's emission sequence number. The report
    marks a rule ACTIVE while its last event is an unresolved fire."""
    _events.emit("alert", rule=rule.metric, severity=rule.severity,
                 value=round(float(value), 6), threshold=rule.threshold,
                 window=window, state=state)
    store = _store
    if store is not None:
        store.append("alerts_active", 1.0 if state == "fire" else 0.0,
                     {"rule": rule.metric})


# --- multi-window burn-rate SLOs ---------------------------------------------
#
# Threshold rules above answer "is the metric bad RIGHT NOW"; an
# error-budget objective answers "is it bad often enough, for long
# enough, to matter". A burn-rate rule declares an objective over a
# scraped series — e.g. "p99 serving latency under 250 ms for 99% of
# samples" — and is evaluated at TWO look-back windows against the
# time-series store: the burn rate of a window is
#
#     (fraction of the window's samples violating the objective)
#     -----------------------------------------------------------
#                 error budget (1 - objective)
#
# so burn 1.0 means "consuming budget exactly as fast as allowed". The
# standard multi-window rule fires only when BOTH windows burn above
# ``max_burn``: the fast window proves the problem is happening *now*
# (and resolves the alert quickly after recovery), the slow window
# proves it is *sustained* (one latency spike never pages). This is the
# signal the router's ``fleet_scale`` verdict reads — a point-in-time
# p99 cannot distinguish a blip from a capacity problem; a burning slow
# window can.

DEFAULT_FAST_WINDOW_S = 300.0    # 5 m
DEFAULT_SLOW_WINDOW_S = 3600.0   # 1 h

# Percentile-stat suffix → the exporter's quantile label on the scraped
# series (serve.metrics._QUANTILES; mean/max are not exported, so burn
# objectives are percentile-only by construction).
_STAT_TO_Q = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}


def burn_selector(metric: str) -> Optional[tuple[str, dict]]:
    """Map a burn-rule metric to its (series, labels) selector in the
    time-series store — ``serving_p99_ms`` → (``serving_ms``,
    ``{"q": "0.99"}``). None when the metric has no scraped series (not
    burn-evaluable)."""
    if metric == "serving_p99_ms":
        return "serving_ms", {"q": "0.99"}
    base, _, stat = metric.rpartition("_")
    if base in WINDOW_METRICS and stat in _STAT_TO_Q:
        return base, {"q": _STAT_TO_Q[stat]}
    return None


def known_burn_metrics() -> set[str]:
    out = {"serving_p99_ms"}
    for m in WINDOW_METRICS:
        out.update(f"{m}_{s}" for s in _STAT_TO_Q)
    return out


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """One error-budget objective: ``value op threshold`` should hold
    for ``objective`` of samples (e.g. ``serving_p99_ms<250@99%``). Note
    ``op`` states the GOOD direction — the opposite convention from
    ``AlertRule``, because an objective declares what health looks
    like."""
    metric: str
    op: str           # "<" (good when below) or ">" (good when above)
    threshold: float
    objective: float  # fraction in (0, 1), e.g. 0.99
    severity: str = "critical"
    fast_s: float = DEFAULT_FAST_WINDOW_S
    slow_s: float = DEFAULT_SLOW_WINDOW_S
    max_burn: float = 1.0

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def bad(self, value: float) -> bool:
        ok = value < self.threshold if self.op == "<" else \
            value > self.threshold
        return not ok

    @property
    def name(self) -> str:
        """The alert-event rule name: the metric with a ``_burn``
        suffix, so a burn alert is distinguishable from the
        point-in-time threshold alert over the same metric."""
        return f"{self.metric}_burn"


# Default serving objective: p99 under the default SLO for 99% of
# scraped samples — 1% error budget, standard 5m/1h window pair.
DEFAULT_BURN_RULES = (
    BurnRateRule("serving_p99_ms", "<", 250.0, 0.99, "critical"),
)

_SLO_RE = re.compile(
    r"^(?P<metric>[a-z0-9_]+)(?P<op>[<>])(?P<threshold>[0-9.eE+-]+)"
    r"@(?P<objective>[0-9.]+)%(?::(?P<severity>[a-z]+))?$"
)


def parse_slos(spec: Optional[str],
               fast_s: float = DEFAULT_FAST_WINDOW_S,
               slow_s: float = DEFAULT_SLOW_WINDOW_S) -> list[BurnRateRule]:
    """Parse a burn-rate SLO spec (comma-separated
    ``metric(<|>)threshold@objective%[:severity]`` entries, e.g.
    ``serving_p99_ms<250@99%:critical``); ``None``/empty = the default
    set. Same config-time refusal convention as ``parse_rules``: a typo
    is an error now, not a silently dead objective later."""
    if not spec:
        return [dataclasses.replace(r, fast_s=fast_s, slow_s=slow_s)
                for r in DEFAULT_BURN_RULES]
    rules: list[BurnRateRule] = []
    seen: set[str] = set()
    valid = known_burn_metrics()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _SLO_RE.match(entry)
        if m is None:
            raise ValueError(
                f"malformed burn-rate SLO {entry!r}: expected "
                "metric(>|<)threshold@objective%[:severity]"
            )
        metric = m.group("metric")
        if metric not in valid:
            raise ValueError(
                f"unknown burn-rate metric {metric!r} in {entry!r}; "
                f"known: {', '.join(sorted(valid))}"
            )
        if metric in seen:
            raise ValueError(f"duplicate SLO metric {metric!r} in {spec!r}")
        seen.add(metric)
        try:
            threshold = float(m.group("threshold"))
            objective = float(m.group("objective")) / 100.0
        except ValueError:
            raise ValueError(
                f"SLO numbers in {entry!r} must be numeric"
            ) from None
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"SLO objective in {entry!r} must be in (0, 100)% "
                "exclusive — a 100% objective has no error budget to burn"
            )
        severity = m.group("severity") or "critical"
        if severity not in SEVERITIES:
            raise ValueError(
                f"unknown SLO severity {severity!r} in {entry!r}; "
                f"one of {', '.join(SEVERITIES)}"
            )
        rules.append(BurnRateRule(metric, m.group("op"), threshold,
                                  objective, severity,
                                  fast_s=fast_s, slow_s=slow_s))
    if not rules:
        raise ValueError(f"empty burn-rate SLO spec {spec!r}")
    return rules


def burn_rate(samples, rule: BurnRateRule, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
    """The burn rate of one look-back window over raw (t, value)
    samples: bad-sample fraction over the error budget. None when the
    window holds no samples (honest absence — an empty window neither
    fires nor resolves on its own authority)."""
    if now is None:
        now = time.time()
    cutoff = now - float(window_s)  # lint: allow-wall-clock(sample axis)
    vals = [v for t, v in samples if t >= cutoff]
    if not vals:
        return None
    bad = sum(1 for v in vals if rule.bad(v))
    return (bad / len(vals)) / rule.budget


class BurnEvaluator:
    """Multi-window burn evaluation over a time-series store, with the
    same fire/resolve hysteresis (and the same ``alert`` event schema)
    as the threshold engine — a burn alert's ``rule`` is
    ``<metric>_burn``, its ``value`` the binding (smaller) window's burn
    rate, its ``threshold`` the ``max_burn`` limit.

    One evaluator instance belongs to one consumer (the fleet router's
    scale loop); ``evaluate()`` is cheap enough to run every verdict
    tick — one store query per rule, both windows cut from the same
    sample list."""

    def __init__(self, store, rules: Optional[list] = None):
        self.store = store
        self.rules = list(DEFAULT_BURN_RULES) if rules is None else \
            list(rules)
        self._active: dict[str, bool] = {}
        self._seq = 0

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: per rule, both windows' burn rates and
        the fire-when-both verdict; emits hysteretic ``alert`` events on
        transitions. Returns ``{metric: {fast, slow, firing, active}}``."""
        if now is None:
            now = time.time()
        self._seq += 1
        out = {}
        for rule in self.rules:
            sel = burn_selector(rule.metric)
            if sel is None:
                continue
            samples = self.store.query(
                sel[0], sel[1], since_s=rule.slow_s, now=now
            )
            fast = burn_rate(samples, rule, rule.fast_s, now)
            slow = burn_rate(samples, rule, rule.slow_s, now)
            firing = (fast is not None and slow is not None
                      and fast > rule.max_burn and slow > rule.max_burn)
            active = self._active.get(rule.metric, False)
            if firing != active:
                # The binding window: both must burn to fire, so the
                # smaller rate is the one that crossed last.
                value = min(v for v in (fast, slow) if v is not None) \
                    if (fast is not None or slow is not None) else 0.0
                fire(AlertRule(rule.name, ">", rule.max_burn,
                               rule.severity),
                     value, self._seq,
                     state="fire" if firing else "resolve")
                self._active[rule.metric] = firing
            out[rule.metric] = {
                "fast": fast, "slow": slow,
                "firing": firing, "active": firing,
            }
        return out

    def active_alerts(self) -> list[str]:
        return sorted(m for m, on in self._active.items() if on)
