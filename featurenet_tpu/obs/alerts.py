"""Declarative SLO alert rules over the rolling-window aggregator.

The windows layer (``obs.windows``) answers "what are the last N steps'
percentiles"; this module answers "is that bad?". A rule is a threshold
over a window-derived metric::

    data_wait_fraction>0.5:warning

evaluated every time the aggregator emits its ``window_summary`` events.
Alerts are HYSTERETIC fire/resolve pairs: a rule crossing into violation
fires ONE ``alert`` event with ``state="fire"`` and then stays silent —
however many emission cycles the violation lasts — until the metric
recovers, which emits the paired ``state="resolve"`` event (``rule`` /
``severity`` / ``value`` / ``threshold`` / ``window`` / ``state``). A
flapping metric produces a fire/resolve pair per flap, never a re-fire
per cycle. Alerts are *never* load-bearing — the engine only ever writes
telemetry, and the sink it writes through already degrades to a no-op on
ENOSPC.

Rule DSL (``Config.alert_rules`` / ``--alert-rules``, comma-separated)::

    metric(>|<)threshold[:severity]

``metric`` is either a derived metric (``DERIVED_METRICS``) or a window
percentile ``<window>_<stat>`` (``data_wait_ms_p99``, ``queue_depth_p50``,
…); severity is one of ``SEVERITIES`` (default ``warning``). A custom
spec *replaces* the defaults — the operator takes full control. A typo'd
metric fails at config time (the same refusal convention as the faults
DSL): a rule that can silently never evaluate is an SLO that tests
nothing.

One rule is cross-host by nature: ``data_wait_spread`` (the max-min
spread of per-host data-wait fractions — free throughput on a lockstep
mesh). No single process can see it, so it carries ``scope="report"``
and is judged where the streams merge: the report/live-tail layer and
the regression gates, not the in-process engine.

Stdlib-only, like the rest of the obs package.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from featurenet_tpu.obs import events as _events

SEVERITIES = ("info", "warning", "critical")

# The raw rolling windows the aggregator maintains (obs.windows keys its
# ring buffers off this tuple — defined here so the rule parser and the
# aggregator can never disagree on the metric universe).
WINDOW_METRICS = (
    "step_ms",          # per-step loop wall (dispatch + paced readback)
    "data_wait_ms",     # host blocked on the prefetcher, per dispatch group
    "queue_depth",      # prefetch queue depth at each consumer pop
    "heartbeat_age_s",  # inter-beat age at each confirmed progress point
    "serving_ms",       # per-request end-to-end serving latency (the
                        # infer_batch span, and the continuous batcher's
                        # enqueue→response interval per request)
    "queue_wait_ms",    # serving front end: request enqueue→dispatch wait
                        # (admission pressure building before latency blows)
    "connect_ms",       # fleet.pool: TCP connect wall per FRESH channel —
                        # the handshake cost pooling exists to amortize; a
                        # pool that stops reusing shows up here as volume
                        # (count climbing), not just latency
    "mfu",              # per-dispatch model-flops utilization (obs.perf:
                        # compiled flops over wall over the device-kind
                        # peak; no samples on the `unknown` peak tier)
    "achieved_bw_fraction",  # per-dispatch bytes-accessed over wall over
                        # the device-kind peak HBM bandwidth (obs.perf)
)

_WINDOW_STATS = ("p50", "p95", "p99", "max", "mean")

# Metrics computed *across* windows rather than read off one of them.
DERIVED_METRICS = (
    "data_wait_fraction",   # sum(data_wait_ms) / sum(step_ms)
    "step_p99_ratio",       # p99(step_ms) / p50(step_ms) — tail blowup
    "heartbeat_age_s",      # max of the heartbeat window
    "queue_depth",          # p50 of the depth window (starvation reads low)
    "serving_p99_ms",       # p99 of the serving window
    "data_wait_spread",     # cross-host; report-scope only (see module doc)
    "mfu",                  # p50 of the mfu window (regression reads LOW:
                            # rules use `<`, e.g. mfu<0.3:warning)
)

REPORT_SCOPE_METRICS = frozenset({"data_wait_spread"})


@dataclasses.dataclass(frozen=True)
class AlertRule:
    metric: str
    op: str  # ">" (higher is worse) or "<" (lower is worse)
    threshold: float
    severity: str = "warning"

    @property
    def scope(self) -> str:
        return ("report" if self.metric in REPORT_SCOPE_METRICS
                else "process")

    def violated(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" else \
            value < self.threshold


# Sane defaults (the ISSUE's four): a starving device, a blown step-time
# tail, a heartbeat going quiet well before the supervisor's 600 s kill,
# and (report-scope) a fat cross-host data-wait spread.
DEFAULT_RULES = (
    AlertRule("data_wait_fraction", ">", 0.5, "warning"),
    AlertRule("step_p99_ratio", ">", 4.0, "warning"),
    AlertRule("heartbeat_age_s", ">", 60.0, "critical"),
    AlertRule("data_wait_spread", ">", 0.25, "warning"),
)


def known_metrics() -> set[str]:
    out = set(DERIVED_METRICS)
    for m in WINDOW_METRICS:
        out.update(f"{m}_{s}" for s in _WINDOW_STATS)
    return out


def is_serving_metric(metric: str) -> bool:
    """Whether a rule metric reads off the serving-side windows (request
    latency / queue wait). The serving front end's drain gate keys off
    this: an unresolved serving alert at drain time exits nonzero
    (``cli serve --drain`` / ``cli infer``), while a training-side alert
    never fails a serving drain."""
    return metric.startswith(("serving", "queue_wait"))


_RULE_RE = re.compile(
    r"^(?P<metric>[a-z0-9_]+)(?P<op>[<>])(?P<threshold>[0-9.eE+-]+)"
    r"(?::(?P<severity>[a-z]+))?$"
)


def parse_rules(spec: Optional[str]) -> list[AlertRule]:
    """Parse an ``--alert-rules`` spec; ``None``/empty = the default set.
    Validates metric names, operators, thresholds, and severities so a
    typo fails the run at config time, not silently at alert time."""
    if not spec:
        return list(DEFAULT_RULES)
    rules: list[AlertRule] = []
    seen: set[str] = set()
    valid = known_metrics()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _RULE_RE.match(entry)
        if m is None:
            raise ValueError(
                f"malformed alert rule {entry!r}: expected "
                "metric(>|<)threshold[:severity]"
            )
        metric = m.group("metric")
        if metric not in valid:
            raise ValueError(
                f"unknown alert metric {metric!r} in {entry!r}; known: "
                f"{', '.join(sorted(valid))}"
            )
        if metric in seen:
            raise ValueError(f"duplicate alert metric {metric!r} in {spec!r}")
        seen.add(metric)
        try:
            threshold = float(m.group("threshold"))
        except ValueError:
            raise ValueError(
                f"alert threshold in {entry!r} must be a number"
            ) from None
        severity = m.group("severity") or "warning"
        if severity not in SEVERITIES:
            raise ValueError(
                f"unknown alert severity {severity!r} in {entry!r}; "
                f"one of {', '.join(SEVERITIES)}"
            )
        rules.append(AlertRule(metric, m.group("op"), threshold, severity))
    if not rules:
        raise ValueError(f"empty alert-rules spec {spec!r}")
    return rules


def fire(rule: AlertRule, value: float, window: int,
         state: str = "fire") -> None:
    """One structured ``alert`` event — ``state="fire"`` when the rule
    crosses into violation, ``state="resolve"`` when it recovers (the
    hysteresis pair; the aggregator tracks which transition this is).
    ``window`` is the aggregator's emission sequence number. The report
    marks a rule ACTIVE while its last event is an unresolved fire."""
    _events.emit("alert", rule=rule.metric, severity=rule.severity,
                 value=round(float(value), 6), threshold=rule.threshold,
                 window=window, state=state)
