"""Performance attribution: compiled-program cost capture, MFU/roofline
accounting, and the live device-memory poller.

The bench series records *what* the system achieves (samples/sec/chip,
inferences/sec/chip); nothing recorded *why* — which programs are
compute-bound vs memory-bound, where HBM headroom actually is, and
whether the quoted MFU is measured or hand-derived. This module makes the
evidence first-class:

- **Per-program cost capture** (``program_cost`` / ``emit_program_cost``):
  every program built through ``Runtime.build`` has its compiled
  ``cost_analysis()`` (flops, bytes accessed, optimal-seconds where the
  backend reports them) and ``memory_analysis()`` (argument/output/temp/
  generated-code bytes, summed into ``peak_bytes``) captured and emitted
  as one ``program_cost`` event. Every field is capture-path-optional:
  a backend with no cost analysis, no memory analysis, or a cost dict
  missing ``flops`` yields an honestly partial record — never a crash,
  never a fabricated number (the sink's never-load-bearing contract).
- **Derived rolling metrics** (``observe_dispatch``): the train loop and
  the serving batcher fold each measured dispatch wall against the
  dispatched program's counters into the ``mfu`` and
  ``achieved_bw_fraction`` rolling windows — achieved FLOP/s (resp.
  bytes/s) over the per-device-kind peak table below. Device kinds with
  no table entry (CPU, a new TPU generation) are an explicit ``unknown``
  tier: no sample is ever synthesized from a missing peak.
- **Roofline classification** (``roofline``): arithmetic intensity
  (flops per byte accessed) against the device's ridge point
  (peak FLOP/s over peak bytes/s) says whether a program is
  compute-bound or memory-bound — which of ROADMAP's remaining
  raw-speed rungs can possibly pay off.
- **Live device-memory watermark** (``sample_device_memory``): an opt-in
  poller (``Config.poll_device_memory``) reads
  ``jax.local_devices()[i].memory_stats()`` on the heartbeat cadence —
  off the hot path by construction — and emits ``device_memory`` events;
  backends without stats (CPU) degrade silently to no events.

Module-level imports are stdlib-only (plus the equally dependency-free
``obs.events``), so the report layer — which must run where the backend
that produced the run is long gone — imports the peak tables and the
roofline verdict from here without dragging in JAX; everything touching
a live backend imports ``jax`` lazily inside the function.
"""

from __future__ import annotations

from typing import Any, Optional

from featurenet_tpu.obs import events as _events

# Peak dense matmul throughput (bf16 FLOP/s) and HBM bandwidth (bytes/s)
# per JAX ``device_kind`` string. Public chip specs; extend this table to
# teach the layer a new accelerator — an absent entry is the explicit
# ``unknown`` tier (no MFU, no roofline), never a guessed peak. v5e
# appears under both strings jax has used for it. THE single source of
# the roofline constants: ``ops/flops.py`` (analytic MFU) and
# ``ops/profile_step.py`` (the step profiler's roofline table) import
# their v5e peaks from here — a spec correction must land once.
PEAK_FLOPS_BY_KIND: dict[str, float] = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}

PEAK_BYTES_PER_SEC_BY_KIND: dict[str, float] = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6e": 1640e9,
}


def device_peaks(device_kind: Optional[str]) -> dict:
    """The peak table row for one device kind: ``tier`` is ``"known"``
    only when a peak FLOP/s entry exists; the ridge point (FLOPs per byte
    at which compute and bandwidth bind equally) exists only when both
    peaks do."""
    kind = device_kind or "unknown"
    pf = PEAK_FLOPS_BY_KIND.get(kind)
    bw = PEAK_BYTES_PER_SEC_BY_KIND.get(kind)
    out: dict = {
        "device_kind": kind,
        "tier": "known" if pf else "unknown",
        "peak_flops": pf,
        "peak_bytes_per_sec": bw,
    }
    if pf and bw:
        out["ridge_flops_per_byte"] = pf / bw
    return out


def local_device_peaks() -> dict:
    """Peaks for this process's first local device; the ``unknown`` tier
    when no backend is reachable (the capture paths all degrade)."""
    try:
        import jax

        return device_peaks(jax.local_devices()[0].device_kind)
    except Exception:
        return device_peaks(None)


# cost_analysis keys worth carrying (source key -> event field).
_COST_KEYS = (
    ("flops", "flops"),
    ("bytes accessed", "bytes"),
    ("optimal_seconds", "optimal_seconds"),
)

# memory_analysis attributes -> event field. peak_bytes is arguments +
# outputs + temps + generated code MINUS the aliased bytes: while the
# program runs those four are simultaneously resident, but a donated
# buffer (the train step's state) is the SAME memory counted once under
# arguments and once under outputs — summing without the alias
# subtraction would overstate the train step's footprint by roughly the
# whole model+optimizer state, and the hbm-headroom verdict ROADMAP
# item 2 consults would read "no room" when there is.
_MEM_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)


def program_cost(compiled: Any) -> dict:
    """Guarded capture of a ``jax.stages.Compiled``'s cost and memory
    analyses. Every field is optional: a backend (or a cache-deserialized
    executable) that cannot answer — missing method, raised error, a cost
    dict without ``flops`` — simply contributes nothing. The result is
    what the backend actually said, possibly ``{}``."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            for src, dst in _COST_KEYS:
                v = ca.get(src)
                if isinstance(v, (int, float)) and v >= 0:
                    out[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        mem = {
            dst: int(v) for src, dst in _MEM_ATTRS
            if isinstance(v := getattr(ma, src, None), (int, float))
            and v >= 0
        }
        if mem:
            out.update(mem)
            additive = [v for k, v in mem.items() if k != "alias_bytes"]
            if additive:
                # Clamped and only derived when an additive field exists:
                # an alias-only (or otherwise partial) capture must yield
                # an absent peak, never a negative fabricated one.
                out["peak_bytes"] = max(
                    0, sum(additive) - mem.get("alias_bytes", 0)
                )
    except Exception:
        pass
    return out


def roofline(flops: Optional[float], bytes_accessed: Optional[float],
             peaks: Optional[dict]) -> Optional[str]:
    """``"compute-bound"`` / ``"memory-bound"`` by arithmetic intensity vs
    the device's ridge point; None whenever any input is missing (an
    unknown device kind, a partial cost capture) — the verdict is never
    fabricated."""
    ridge = (peaks or {}).get("ridge_flops_per_byte")
    if not flops or not bytes_accessed or not ridge:
        return None
    return ("compute-bound" if flops / bytes_accessed >= ridge
            else "memory-bound")


def emit_program_cost(name: str, compiled: Any,
                      peaks: Optional[dict] = None,
                      precision: Optional[str] = None) -> dict:
    """Capture ``compiled``'s cost and emit one ``program_cost`` event
    (``Runtime.build``'s hook). The event always carries ``program`` and
    ``device_kind``; ``precision`` (the program's weight-precision label
    — fp32 / bf16_master / int8) rides along when the caller knows it,
    so the report's per-program table can attribute a precision-rung
    delta to the executable that ran it. Everything else is whatever
    the backend could say. Returns the cost dict so the caller can keep
    it next to the executable (``CompiledProgram.cost``)."""
    cost = program_cost(compiled)
    if peaks is None:
        peaks = local_device_peaks()
    extra = {"precision": precision} if precision else {}
    _events.emit("program_cost", program=name,
                 device_kind=peaks.get("device_kind"), **extra, **cost)
    return cost


def mfu_value(cost: Optional[dict], wall_s: float,
              peaks: Optional[dict]) -> Optional[float]:
    """Achieved MFU of one measured wall — compiled flops over wall over
    the device-kind peak — or None when flops, the peak, or the wall is
    missing. The ONE formula: ``observe_dispatch`` and both bench
    measurements (``mfu_train``, ``serve_mfu``) call this, so a guard or
    unit change can never land in one copy and miss the others."""
    if not cost or not peaks or wall_s <= 0:
        return None
    pf = peaks.get("peak_flops")
    fl = cost.get("flops")
    if not pf or not fl:
        return None
    return fl / wall_s / pf


def observe_dispatch(cost: Optional[dict], wall_s: float,
                     peaks: Optional[dict] = None) -> dict:
    """Fold one measured dispatch wall against the dispatched program's
    compiled counters into the rolling ``mfu`` / ``achieved_bw_fraction``
    windows. Returns the derived sample(s); empty when nothing is
    derivable (no cost, unknown peak tier, zero wall) — a missing peak
    must yield an absent metric, never a fabricated one."""
    out: dict = {}
    if not cost or not peaks or wall_s <= 0:
        return out
    from featurenet_tpu.obs import windows as _windows

    m = mfu_value(cost, wall_s, peaks)
    if m is not None:
        out["mfu"] = m
        _windows.observe("mfu", m)
    bw = peaks.get("peak_bytes_per_sec")
    by = cost.get("bytes")
    if bw and by:
        out["achieved_bw_fraction"] = by / wall_s / bw
        _windows.observe("achieved_bw_fraction", out["achieved_bw_fraction"])
    return out


def sample_device_memory() -> list[dict]:
    """Poll every local device's ``memory_stats()`` and emit one
    ``device_memory`` event per device that answered. Backends without
    stats (CPU returns None) degrade silently to an empty list — the
    poller is opt-in telemetry, never load-bearing. Callers run this on
    the heartbeat cadence, off the dispatch hot path."""
    rows: list[dict] = []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return rows
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not isinstance(stats, dict):
            continue
        used = stats.get("bytes_in_use")
        if not isinstance(used, (int, float)):
            continue
        extra = {
            dst: int(stats[src]) for src, dst in (
                ("peak_bytes_in_use", "peak_bytes_in_use"),
                ("bytes_limit", "bytes_limit"),
            ) if isinstance(stats.get(src), (int, float))
        }
        row = {"device": int(getattr(d, "id", len(rows))),
               "bytes_in_use": int(used), **extra}
        rows.append(row)
        _events.emit("device_memory", device=row["device"],
                     bytes_in_use=row["bytes_in_use"], **extra)
    return rows
