"""Alert-triggered incident bundles: the diagnosis rung above alerting.

The pipeline below this module *detects* trouble — rolling windows,
fire/resolve threshold alerts, multi-window burn-rate SLOs, gate
regressions, replica-loss events. What it cannot do is *diagnose* after
the fact: by the time an operator runs ``cli report`` the bad minute's
window snapshots are overwritten, the unsampled request timelines are
dropped, the roster has healed, and host-side Python time was never
recorded at all. An **incident** freezes all of that at the moment an
alert fires, into a self-contained bundle under
``<run_dir>/incidents/<id>/``:

- ``manifest.json``  — the triggering rule/value/severity/threshold,
  open/close times, duration, capture inventory (atomic tmp+replace,
  like every manifest in the repo).
- ``tsdb.json``      — a slice of EVERY series in the run's time-series
  store over a lookback window: what the fleet looked like leading in.
- ``windows.json``   — the live per-metric window snapshots at fire
  time (the exact numbers the alert judged).
- ``roster.json``    — membership state (fleet/elastic runs), copied
  verbatim.
- ``events_tail.jsonl`` — the recent tail of every per-host event
  stream, tagged with its stream — including the force-sampled request
  timelines below.
- ``stacks.folded``  — N seconds of folded thread stacks with thread
  names (``obs.stacksampler``): where host CPU time went during the
  bad window, the host-side complement to the perf layer's device cost
  attribution.

While any incident is open, request tracing **force-samples every
request** (``tracing.set_force_all``) — the tail-bias hook already
existed; an incident widens it to everything, so the bundle's events
tail holds complete timelines from the incident window.

Flap damping borrows the autoscaler's discipline: at most ONE open
incident per rule while its alert is unresolved, and a post-close
**cooldown** before the same rule may open another — a flapping metric
produces one bundle per cooldown window, never one per fire/resolve
pair. ``gate_regression`` and replica-loss storms (several losses
inside a short window) open one-shot incidents that capture and
self-close.

Durability is the sink/tsdb contract: telemetry is never load-bearing.
The first ``OSError`` on any bundle write puts the manager dark for the
run (drops counted, one stderr warning); the bundle count is bounded
with oldest-first pruning; readers (``cli incident show``, the report)
tolerate torn manifests and missing files by naming what is missing.

Subscription is a module-level event tap on the sink
(``events.set_tap``): the manager sees every event the process emits —
``alert`` fire/resolve transitions (threshold AND burn rules share that
one funnel), ``supervisor``/``gate_regression``, ``fleet_replica_loss``
— with no per-callsite wiring. Stdlib-only, like the rest of ``obs``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
from typing import Optional

from featurenet_tpu.obs import events as _events
from featurenet_tpu.obs import stacksampler as _stacksampler
from featurenet_tpu.obs import tracing as _tracing
from featurenet_tpu.obs import tsdb as _tsdb
from featurenet_tpu.obs import windows as _windows

INCIDENTS_DIRNAME = "incidents"
MANIFEST_FILENAME = "manifest.json"

DEFAULT_COOLDOWN_S = 60.0
DEFAULT_LOOKBACK_S = 600.0
DEFAULT_MAX_BUNDLES = 16
DEFAULT_SAMPLE_S = 2.0

# A replica-loss storm: this many ``fleet_replica_loss`` events inside
# the window. One loss is the fleet's bread and butter (respawn,
# re-submit, rejoin); a cluster of them is a correlated failure worth a
# bundle.
STORM_THRESHOLD = 3
STORM_WINDOW_S = 60.0

# Per-stream tail length for events_tail.jsonl: enough to hold the
# incident window's force-sampled timelines without archiving the run.
EVENTS_TAIL_LINES = 400

# The bundle inventory a complete capture writes (manifest excluded —
# it is the inventory). roster.json is optional by nature: standalone
# serves have no membership document, and its absence is not damage.
BUNDLE_FILES = ("tsdb.json", "windows.json", "events_tail.jsonl",
                "stacks.folded")


def incidents_dir(run_dir: str) -> str:
    return os.path.join(os.path.abspath(run_dir), INCIDENTS_DIRNAME)


class IncidentManager:
    """One process's incident plane over one run directory.

    Armed via ``incidents.arm(run_dir)`` (which installs the event tap);
    ``InferenceService`` and ``FleetRouter`` arm one when they have a
    run_dir. All mutable state is guarded by ``self._lock`` — the tap
    calls ``on_event`` from whatever thread emitted the event.
    """

    def __init__(self, run_dir: str, *,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 lookback_s: float = DEFAULT_LOOKBACK_S,
                 max_bundles: int = DEFAULT_MAX_BUNDLES,
                 sample_s: float = DEFAULT_SAMPLE_S,
                 sample_hz: float = _stacksampler.DEFAULT_HZ):
        self.run_dir = os.path.abspath(run_dir)
        self.dir = incidents_dir(run_dir)
        self.cooldown_s = float(cooldown_s)
        self.lookback_s = float(lookback_s)
        self.max_bundles = int(max_bundles)
        self.sample_s = float(sample_s)
        self.sample_hz = float(sample_hz)
        self._lock = threading.Lock()
        self._open: dict[str, dict] = {}       # rule -> live manifest
        self._t0: dict[str, float] = {}        # rule -> perf_counter open
        self._cooldown: dict[str, float] = {}  # rule -> monotonic close
        self._loss_times: list[float] = []     # storm window (monotonic)
        self._threads: list[threading.Thread] = []
        self._opened_total = 0
        self._dropped = 0
        self._dark = False
        self._disarmed = False

    # -- the event tap (called from the emitting thread) ---------------------
    def on_event(self, ev: str, record: dict) -> None:
        """Dispatch one sink event. Must never raise into the emit path
        (the tap caller guards, but the discipline starts here) and must
        never do heavy work: opening an incident is bookkeeping plus a
        capture-thread spawn; the caller may hold the windows lock."""
        if ev == "alert":
            rule = record.get("rule")
            if not isinstance(rule, str):
                return
            if record.get("state") == "fire":
                self._maybe_open(
                    rule, severity=str(record.get("severity", "warning")),
                    value=record.get("value"),
                    threshold=record.get("threshold"),
                )
            elif record.get("state") == "resolve":
                self._close(rule)
        elif ev == "supervisor" \
                and record.get("phase") == "gate_regression":
            failed = record.get("failed") or ()
            self._maybe_open(
                "gate_regression", severity="critical",
                value=float(len(failed)), threshold=0.0,
                one_shot=True, detail={"failed": list(failed)},
            )
        elif ev == "fleet_replica_loss":
            with self._lock:
                now = time.monotonic()
                self._loss_times.append(now)
                self._loss_times = [
                    t for t in self._loss_times
                    if now - t <= STORM_WINDOW_S
                ]
                storm = len(self._loss_times) >= STORM_THRESHOLD
                losses = len(self._loss_times)
            if storm:
                self._maybe_open(
                    "replica_loss_storm", severity="critical",
                    value=float(losses), threshold=float(STORM_THRESHOLD),
                    one_shot=True,
                )

    # -- open / close ---------------------------------------------------------
    def _maybe_open(self, rule: str, *, severity: str, value, threshold,
                    one_shot: bool = False,
                    detail: Optional[dict] = None) -> None:
        with self._lock:
            if self._disarmed or self._dark:
                return
            if rule in self._open:
                return  # at most one open incident per rule
            last = self._cooldown.get(rule)
            if last is not None and \
                    time.monotonic() - last < self.cooldown_s:
                return  # flap damping: the autoscaler's cooldown move
            import datetime

            t_open = time.time()
            man = {
                "id": f"inc-{int(t_open * 1000):013d}-{rule}",
                "rule": rule,
                "severity": severity,
                "value": value,
                "threshold": threshold,
                "state": "open",
                "opened_unix": round(t_open, 3),
                "opened_time": datetime.datetime.fromtimestamp(
                    t_open, datetime.timezone.utc
                ).isoformat(timespec="seconds"),
                "pid": os.getpid(),
                "one_shot": bool(one_shot),
            }
            if detail:
                man.update(detail)
            self._open[rule] = man
            self._t0[rule] = time.perf_counter()
            self._opened_total += 1
            # Incident mode: every request's timeline is kept while ANY
            # incident is open — the bundle's events tail must hold the
            # bad window's complete traces, not a sample of them.
            if len(self._open) == 1:
                _tracing.set_force_all(True)
            self._threads = [t for t in self._threads if t.is_alive()]
            th = threading.Thread(  # lint: allow-thread-leak(tracked in self._threads, joined in disarm)
                target=self._capture, args=(man, one_shot),
                name="incident-capture", daemon=True,
            )
            self._threads.append(th)
            th.start()
        _events.emit("incident_open", id=man["id"], rule=rule,
                     severity=severity, value=value,
                     threshold=threshold)

    def _close(self, rule: str) -> None:
        with self._lock:
            man = self._open.pop(rule, None)
            if man is None:
                return
            t0 = self._t0.pop(rule, None)
            self._cooldown[rule] = time.monotonic()
            man["state"] = "closed"
            man["duration_s"] = (
                round(time.perf_counter() - t0, 3) if t0 is not None
                else 0.0
            )
            man["closed_unix"] = round(time.time(), 3)
            if not self._open:
                _tracing.set_force_all(False)
        self._write_manifest(man)
        _events.emit("incident_close", id=man["id"], rule=rule,
                     duration_s=man["duration_s"])

    # -- the capture thread ---------------------------------------------------
    def _capture(self, man: dict, one_shot: bool) -> None:
        """Write the bundle. Runs on its own daemon thread so the alert
        path never waits on disk or the sampler; every write is absorbed
        by the go-dark discipline."""
        bundle = os.path.join(self.dir, man["id"])
        try:
            os.makedirs(bundle, exist_ok=True)
            self._write_manifest(man)
            files = []
            self._write_atomic(bundle, "tsdb.json",
                               json.dumps(self._tsdb_slice(), indent=1))
            files.append("tsdb.json")
            self._write_atomic(bundle, "windows.json", json.dumps(
                {"windows": _windows.snapshot()}, indent=1
            ))
            files.append("windows.json")
            roster = self._read_roster()
            if roster is not None:
                self._write_atomic(bundle, "roster.json", roster)
                files.append("roster.json")
            self._write_atomic(bundle, "events_tail.jsonl",
                               self._events_tail())
            files.append("events_tail.jsonl")
            # Stacks last: the sampler spends sample_s of wall, and the
            # cheap snapshots above should be as close to fire time as
            # possible. Hard deadline inside the sampler; a truncated
            # (partial) profile is kept and marked.
            profile = _stacksampler.sample_stacks(
                self.sample_s, hz=self.sample_hz
            )
            self._write_atomic(bundle, "stacks.folded",
                               _stacksampler.render_folded(profile))
            files.append("stacks.folded")
            with self._lock:
                man["files"] = files
                man["capture"] = {
                    "stack_samples": profile["samples"],
                    "stack_ticks": profile["ticks"],
                    "stack_duration_s": profile["duration_s"],
                    "stack_truncated": profile["truncated"],
                }
            self._write_manifest(man)
            _events.emit("incident_capture", id=man["id"], files=files)
            self._prune()
        except OSError as e:
            self._go_dark(e)
        finally:
            if one_shot:
                # gate_regression / loss storm: no paired resolve event
                # will ever arrive — the capture window IS the incident.
                self._close(man["rule"])

    # -- bundle pieces --------------------------------------------------------
    def _tsdb_slice(self) -> dict:
        """Every series in the run's store over the lookback window — a
        fresh read-only handle; the scraper (when there is one) stays
        the store's one writer."""
        now = time.time()
        series = []
        store = _tsdb.TimeSeriesStore.open(self.run_dir)
        try:
            for metric, labels in store.series():
                samples = store.query(metric, labels,
                                      since_s=self.lookback_s, now=now)
                if samples:
                    series.append({
                        "metric": metric,
                        "labels": labels,
                        "samples": [[round(t, 3), v] for t, v in samples],
                    })
        finally:
            store.close()
        return {"lookback_s": self.lookback_s,
                "now_unix": round(now, 3), "series": series}

    def _read_roster(self) -> Optional[str]:
        """membership.json verbatim (fleet/elastic runs); None when the
        run has no roster — absence is normal, not damage."""
        path = os.path.join(self.run_dir, "membership.json")
        try:
            with open(path, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def _events_tail(self) -> str:
        """The recent tail of every per-host event stream, each record
        re-tagged with its stream. Reads tolerate live writers: only
        whole, parseable lines are kept (the torn-tail discipline every
        reader in the repo follows)."""
        out: list[str] = []
        try:
            names = sorted(
                n for n in os.listdir(self.run_dir)
                if n.startswith("events") and n.endswith(".jsonl")
            )
        except OSError:
            return ""
        for name in names:
            path = os.path.join(self.run_dir, name)
            try:
                with open(path, "rb") as fh:
                    fh.seek(0, os.SEEK_END)
                    size = fh.tell()
                    # ~enough bytes for the tail without re-reading a
                    # long run's whole stream.
                    back = min(size, EVENTS_TAIL_LINES * 512)
                    fh.seek(size - back)
                    raw = fh.read().decode("utf-8", "replace")
            except OSError:
                continue
            lines = raw.splitlines()
            if back < size and lines:
                lines = lines[1:]  # first line may start mid-record
            for line in lines[-EVENTS_TAIL_LINES:]:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a live stream
                rec["stream"] = name
                out.append(json.dumps(rec, default=str))
        return "\n".join(out) + ("\n" if out else "")

    # -- durability -----------------------------------------------------------
    def _write_manifest(self, man: dict) -> None:
        bundle = os.path.join(self.dir, man["id"])
        try:
            os.makedirs(bundle, exist_ok=True)
            with self._lock:
                # Serialize + write under the lock: the capture thread
                # and a resolve-driven close may both rewrite the (one,
                # shared) manifest dict; last write carries both sides'
                # fields because the dict is shared.
                data = json.dumps(dict(man), indent=1, default=str)
                tmp = os.path.join(bundle, MANIFEST_FILENAME + ".tmp")
                with open(tmp, "w", encoding="utf-8") as fh:
                    fh.write(data)
                os.replace(tmp,
                           os.path.join(bundle, MANIFEST_FILENAME))
        except OSError as e:
            self._go_dark(e)

    def _write_atomic(self, bundle: str, name: str, text: str) -> None:
        tmp = os.path.join(bundle, name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, os.path.join(bundle, name))

    def _prune(self) -> None:
        """Bound the bundle count, oldest first (ids sort by open time);
        open incidents are never pruned out from under their capture."""
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if os.path.isdir(os.path.join(self.dir, n))
            )
        except OSError:
            return
        with self._lock:
            keep = {m["id"] for m in self._open.values()}
        excess = len(names) - self.max_bundles
        for name in names:
            if excess <= 0:
                break
            if name in keep:
                continue
            shutil.rmtree(os.path.join(self.dir, name),
                          ignore_errors=True)
            excess -= 1

    def _go_dark(self, e: Exception) -> None:
        """First OSError on any bundle write: the incident plane goes
        dark for the run — one stderr warning, drops counted, the
        serving path never notices (telemetry is never load-bearing)."""
        with self._lock:
            self._dropped += 1
            first = not self._dark
            self._dark = True
        if first:
            print(json.dumps({
                "incident_error": f"incident bundle write failed "
                f"({type(e).__name__}: {e}); incident capture for this "
                "process goes dark, serving continues",
                "dir": self.dir,
            }), file=sys.stderr)

    # -- introspection / lifecycle --------------------------------------------
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def open_ids(self) -> list[str]:
        with self._lock:
            return sorted(m["id"] for m in self._open.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "open": len(self._open),
                "opened_total": self._opened_total,
                "dropped": self._dropped,
                "dark": self._dark,
            }

    def disarm(self) -> None:
        """Close any open incidents (with their true duration), join the
        capture threads, drop force-sampling. Bundles stay on disk — they
        are the point."""
        with self._lock:
            if self._disarmed:
                return
            self._disarmed = True
            rules = list(self._open)
            threads = list(self._threads)
        for rule in rules:
            self._close(rule)
        for th in threads:
            th.join(timeout=10.0)
        _tracing.set_force_all(False)


# --- module-level (process-wide) manager -------------------------------------

_manager: Optional[IncidentManager] = None
_slot_lock = threading.Lock()


def arm(run_dir: str, **kw) -> IncidentManager:
    """Install the process-wide manager for ``run_dir`` (idempotent per
    directory, like ``events.init_run``: re-arming the same run returns
    the live manager; a different run swaps it)."""
    global _manager
    old = None
    with _slot_lock:
        if (_manager is not None and not _manager._disarmed
                and _manager.run_dir == os.path.abspath(run_dir)):
            return _manager
        old = _manager
        _manager = IncidentManager(run_dir, **kw)
        _events.set_tap(_manager.on_event)
        mgr = _manager
    if old is not None:
        old.disarm()
    return mgr


def disarm(manager: Optional[IncidentManager] = None) -> None:
    """Disarm ``manager`` (default: the installed one); uninstalls the
    event tap when it is the installed one. A stale handle (already
    swapped out by a later ``arm``) only disarms itself."""
    global _manager
    with _slot_lock:
        m = manager if manager is not None else _manager
        if m is not None and m is _manager:
            _events.set_tap(None)
            _manager = None
    if m is not None:
        m.disarm()


def manager() -> Optional[IncidentManager]:
    return _manager


def open_count() -> int:
    m = _manager
    return m.open_count() if m is not None else 0


def reset() -> None:
    """Drop ALL process-wide incident state (tap, manager, the tracing
    force-all flag) — the test-suite hygiene hook, mirroring
    ``obs.close_run``."""
    disarm()
    _tracing.set_force_all(False)


# --- reading bundles (post-hoc: cli incident / report / dash) ----------------


def list_incidents(run_dir: str) -> list[dict]:
    """Every bundle under ``<run_dir>/incidents``, oldest first, from
    the manifests alone — damaged manifests yield a ``damaged`` entry
    instead of an exception (the post-mortem reader's contract)."""
    base = incidents_dir(run_dir)
    out: list[dict] = []
    try:
        names = sorted(n for n in os.listdir(base)
                       if os.path.isdir(os.path.join(base, n)))
    except OSError:
        return out
    for name in names:
        entry: dict = {"id": name}
        try:
            with open(os.path.join(base, name, MANIFEST_FILENAME),
                      encoding="utf-8") as fh:
                man = json.load(fh)
            for k in ("rule", "severity", "state", "value", "threshold",
                      "opened_time", "duration_s", "one_shot"):
                if k in man:
                    entry[k] = man[k]
        except (OSError, ValueError):
            entry["state"] = "damaged"
        out.append(entry)
    return out


def load_bundle(run_dir: str, incident_id: str) -> dict:
    """One bundle, degradation-tolerant: every absent or unparseable
    piece lands in ``missing`` (with why) instead of raising — a torn
    manifest, a pruned tsdb slice, a half-written stacks file must
    produce a post-mortem that NAMES the damage, never a traceback."""
    bundle = os.path.join(incidents_dir(run_dir), incident_id)
    out: dict = {
        "id": incident_id, "dir": bundle,
        "manifest": None, "tsdb": None, "windows": None,
        "roster": None, "events_tail": [], "stacks": None,
        "missing": [],
    }
    if not os.path.isdir(bundle):
        out["missing"].append(f"{bundle} (no such bundle)")
        return out

    def _read(name: str) -> Optional[str]:
        try:
            with open(os.path.join(bundle, name),
                      encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            out["missing"].append(f"{name} (absent)")
            return None

    raw = _read(MANIFEST_FILENAME)
    if raw is not None:
        try:
            out["manifest"] = json.loads(raw)
        except ValueError:
            out["missing"].append(
                f"{MANIFEST_FILENAME} (torn/unparseable JSON)"
            )
    for key, name in (("tsdb", "tsdb.json"), ("windows", "windows.json")):
        if not os.path.exists(os.path.join(bundle, name)):
            out["missing"].append(f"{name} (absent)")
            continue
        raw = _read(name)
        if raw is None:
            continue
        try:
            out[key] = json.loads(raw)
        except ValueError:
            out["missing"].append(f"{name} (torn/unparseable JSON)")
    roster_path = os.path.join(bundle, "roster.json")
    if os.path.exists(roster_path):
        raw = _read("roster.json")
        if raw is not None:
            try:
                out["roster"] = json.loads(raw)
            except ValueError:
                out["missing"].append("roster.json (torn/unparseable JSON)")
    tail_path = os.path.join(bundle, "events_tail.jsonl")
    if os.path.exists(tail_path):
        raw = _read("events_tail.jsonl")
        for line in (raw or "").splitlines():
            try:
                out["events_tail"].append(json.loads(line))
            except ValueError:
                continue
    else:
        out["missing"].append("events_tail.jsonl (absent)")
    stacks_path = os.path.join(bundle, "stacks.folded")
    if os.path.exists(stacks_path):
        raw = _read("stacks.folded")
        if raw is not None:
            out["stacks"] = _stacksampler.parse_folded(raw)
    else:
        out["missing"].append("stacks.folded (absent)")
    return out


def format_incident(bundle: dict) -> str:
    """The rendered post-mortem, from the bundle dict alone (no live
    process, no store handle): header, timeline, tsdb/window highlights,
    roster, events-tail census, per-thread stack totals — and an
    explicit ``missing:`` section naming every degraded piece."""
    man = bundle.get("manifest") or {}
    lines = [
        f"incident {bundle['id']}",
        f"  rule: {man.get('rule', '?')} · severity "
        f"{man.get('severity', '?')} · state {man.get('state', '?')}",
    ]
    if man.get("value") is not None:
        lines.append(
            f"  trigger: value {man.get('value')} vs threshold "
            f"{man.get('threshold')}"
        )
    if man.get("opened_time"):
        lines.append(f"  opened: {man['opened_time']}")
    if man.get("duration_s") is not None:
        lines.append(f"  duration: {man['duration_s']}s")
    if man.get("one_shot"):
        lines.append("  one-shot capture (no paired resolve)")
    cap = man.get("capture") or {}
    tsdb = bundle.get("tsdb")
    if tsdb is not None:
        n_series = len(tsdb.get("series") or [])
        n_samples = sum(len(s.get("samples") or ())
                        for s in tsdb.get("series") or [])
        lines.append(
            f"  tsdb slice: {n_series} series, {n_samples} samples over "
            f"{tsdb.get('lookback_s', '?')}s lookback"
        )
    win = (bundle.get("windows") or {}).get("windows") or {}
    if win:
        tops = ", ".join(
            f"{m} p99={s.get('p99')}" for m, s in sorted(win.items())[:4]
        )
        lines.append(f"  windows at fire: {tops}")
    roster = bundle.get("roster")
    if roster is not None:
        hosts = roster.get("members") or roster.get("hosts") or []
        lines.append(f"  roster: {len(hosts)} member(s), generation "
                     f"{roster.get('generation', '?')}")
    tail = bundle.get("events_tail") or []
    if tail:
        kinds: dict[str, int] = {}
        for rec in tail:
            k = rec.get("ev", "?")
            kinds[k] = kinds.get(k, 0) + 1
        census = ", ".join(f"{k}:{n}" for k, n in
                           sorted(kinds.items(), key=lambda kv: -kv[1])[:8])
        lines.append(f"  events tail: {len(tail)} records ({census})")
    stacks = bundle.get("stacks")
    if stacks:
        totals = _stacksampler.thread_totals(stacks)
        top = ", ".join(
            f"{name}:{n}" for name, n in
            sorted(totals.items(), key=lambda kv: -kv[1])[:6]
        )
        extra = ""
        if cap.get("stack_truncated"):
            extra = " (truncated at the sampler deadline; partial)"
        lines.append(
            f"  stacks: {sum(stacks.values())} samples across "
            f"{len(totals)} thread(s){extra} — {top}"
        )
        for stack, count in sorted(
                stacks.items(), key=lambda kv: -kv[1])[:3]:
            lines.append(f"    {count:>5}  {stack}")
    missing = bundle.get("missing") or []
    if missing:
        lines.append("  missing: " + "; ".join(missing))
    return "\n".join(lines) + "\n"
