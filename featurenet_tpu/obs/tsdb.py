"""Append-only, run_dir-resident time-series store: the fleet's durable
signal plane.

Everything the live layers keep — rolling windows, pool counters,
batcher stats — dies with its process. The store is what survives: the
fleet scraper (``fleet.scraper``) appends every sample it collects to
``<run_dir>/tsdb/``, and the burn-rate evaluator, ``cli dash``, and the
report's fleet-timeline section all answer "what did p99 look like over
the last hour, per replica" from these files alone — after every serving
process has exited.

Layout: one JSONL segment sequence per metric×labels series, the series
identity encoded in the filename (``serving_ms;q=0.99;replica=0``
→ ``serving_ms;q=0.99;replica=0.000000.jsonl``). Each line is one
``{"t": epoch_seconds, "v": value}`` sample written with the event
sink's durability discipline: O_APPEND fd, ONE ``os.write`` per complete
line — concurrent writers interleave whole lines, a crash tears at most
the final line. Readers skip torn tails and unparsable lines instead of
failing (the same contract as the report's event loader), and merge a
series' segments in timestamp order.

Ring pruning: segments rotate at ``segment_bytes``; on every rotation
the store drops closed segments older than ``max_age_s`` and then
oldest-first until the directory fits ``max_bytes`` — so an arbitrarily
long-lived fleet holds a bounded, recent history, like a Prometheus TSDB
head block without the index machinery.

Never load-bearing: the first OSError (disk full, permissions, a
deleted run_dir) puts the writer in the dark — every later ``append`` is
a counter bump and nothing else. Collection must not be able to take
down the serving path it observes.

Timestamps are wall-clock epoch seconds *by design*: samples from three
processes (router + N replicas) must land on one comparable axis, and
the axis must still mean something when the store is read days later.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

# ONE percentile implementation across live windows, report, and store:
# nearest-rank, shared with obs.report/obs.windows.
from featurenet_tpu.obs.report import _pct

STORE_DIRNAME = "tsdb"

DEFAULT_SEGMENT_BYTES = 256 * 1024
DEFAULT_MAX_BYTES = 64 * 1024 * 1024
DEFAULT_MAX_AGE_S = 24 * 3600.0

_SEG_SUFFIX = ".jsonl"
_SEG_WIDTH = 6

# Filename-safe charset for metric names and label keys/values. Anything
# else becomes "_" — labels here are Prometheus label values (replica
# slots, quantiles, outcomes, version strings), which fit comfortably.
_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.-="
)


def _sanitize(token: str) -> str:
    return "".join(c if c in _SAFE else "_" for c in str(token))


def series_key(metric: str, labels: Optional[dict] = None) -> str:
    """The canonical series identity: metric then sorted ``k=v`` pairs,
    ``;``-joined. This string IS the segment filename stem, so two
    writers composing the same (metric, labels) append to the same
    series no matter the dict order."""
    parts = [_sanitize(metric)]
    for k in sorted(labels or {}):
        parts.append(f"{_sanitize(k)}={_sanitize(labels[k])}")
    return ";".join(parts)


def parse_series_key(key: str) -> tuple[str, dict]:
    """Inverse of ``series_key`` (modulo sanitization): filename stem →
    (metric, labels)."""
    parts = key.split(";")
    labels = {}
    for p in parts[1:]:
        k, _, v = p.partition("=")
        labels[k] = v
    return parts[0], labels


def store_dir(run_dir: str) -> str:
    return os.path.join(run_dir, STORE_DIRNAME)


class TimeSeriesStore:
    """Writer + reader over one ``<run_dir>/tsdb`` directory.

    The writer half (``append``) is what the scraper holds; the reader
    half (``query``/``percentile``/``series``) re-scans the directory on
    every call, so a store opened read-only on a *finished* run_dir —
    or on one another process is still appending to — needs no writer
    state at all.
    """

    def __init__(self, root: str, *,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_age_s: float = DEFAULT_MAX_AGE_S):
        self.root = os.path.abspath(root)
        self.segment_bytes = int(segment_bytes)
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        self._lock = threading.Lock()
        # Per-series writer state: key -> [fd, seg_index, bytes_in_seg].
        self._writers: dict[str, list] = {}
        self._dark = False
        self.appended = 0
        self.dropped = 0

    @classmethod
    def open(cls, run_dir: str, **kw) -> "TimeSeriesStore":
        """The store of one run directory (``<run_dir>/tsdb``)."""
        return cls(store_dir(run_dir), **kw)

    # -- write path ----------------------------------------------------------
    def append(self, metric: str, value, labels: Optional[dict] = None,
               t: Optional[float] = None) -> bool:
        """Append one sample; True when it durably landed. Every failure
        path is absorbed: a dark store drops samples and counts them —
        telemetry is never load-bearing."""
        if self._dark:
            # Same discipline as ``appended``: concurrent appenders are
            # supported, so the counter read-modify-write takes the lock.
            with self._lock:
                self.dropped += 1
            return False
        if t is None:
            t = time.time()
        line = json.dumps(
            {"t": round(float(t), 3), "v": float(value)},
            separators=(",", ":"),
        ).encode("utf-8") + b"\n"
        key = series_key(metric, labels)
        try:
            with self._lock:
                st = self._writers.get(key)
                if st is None:
                    st = self._open_writer_locked(key)
                    self._writers[key] = st
                elif st[2] + len(line) > self.segment_bytes and st[2] > 0:
                    self._rotate_locked(key, st)
                # One write, one complete line: concurrent appenders
                # interleave whole samples (O_APPEND), a crash tears at
                # most the tail the readers already skip.
                os.write(st[0], line)
                st[2] += len(line)
                self.appended += 1
            return True
        except OSError:
            # Disk full / unlinked root / fd limit: go dark for good.
            # A degraded store must never raise into the scrape loop.
            self._go_dark()
            with self._lock:
                self.dropped += 1
            return False

    def close(self) -> None:
        with self._lock:
            for st in self._writers.values():
                try:
                    os.close(st[0])
                except OSError:
                    pass
            self._writers.clear()

    def _go_dark(self) -> None:
        self._dark = True
        self.close()

    def _open_writer_locked(self, key: str) -> list:
        os.makedirs(self.root, exist_ok=True)
        # Resume the highest existing segment so a reopened store (a
        # respawned scraper) keeps one ordered sequence per series.
        seg = 0
        for _, idx, _p in self._segments_of(key):
            seg = max(seg, idx)
        path = self._seg_path(key, seg)
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
        size = os.fstat(fd).st_size
        if size >= self.segment_bytes:
            os.close(fd)
            seg += 1
            path = self._seg_path(key, seg)
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
            size = os.fstat(fd).st_size
        # A resumed segment ending mid-line is a predecessor's torn
        # tail. Terminate it before appending: otherwise the first new
        # sample would fuse with the tear into one unparsable line and
        # both would be lost to the reader's skip.
        if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
            size += os.write(fd, b"\n")
        return [fd, seg, size]

    def _rotate_locked(self, key: str, st: list) -> None:
        try:
            os.close(st[0])
        except OSError:
            pass
        st[1] += 1
        path = self._seg_path(key, st[1])
        st[0] = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
        st[2] = 0
        self._prune_locked()

    def _prune_locked(self) -> None:
        """Drop closed segments by age, then oldest-first to the byte
        budget. Active (currently-open) segments are never deleted."""
        active = {
            self._seg_path(k, st[1]) for k, st in self._writers.items()
        }
        segs = []  # (mtime, size, path)
        for key in self._series_keys():
            for path, _idx, stat in self._segments_of(key):
                if path in active:
                    continue
                segs.append((stat.st_mtime, stat.st_size, path))
        segs.sort()
        now = time.time()
        total = sum(s[1] for s in segs)
        for mtime, size, path in segs:
            age = now - mtime  # lint: allow-wall-clock(mtime is epoch-based)
            too_old = age > self.max_age_s
            if not too_old and total <= self.max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                pass
            total -= size

    # -- directory scan ------------------------------------------------------
    def _seg_path(self, key: str, seg: int) -> str:
        return os.path.join(
            self.root, f"{key}.{seg:0{_SEG_WIDTH}d}{_SEG_SUFFIX}"
        )

    def _series_keys(self) -> list[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        keys = set()
        for n in names:
            if not n.endswith(_SEG_SUFFIX):
                continue
            stem = n[: -len(_SEG_SUFFIX)]
            stem, _, seg = stem.rpartition(".")
            if stem and seg.isdigit():
                keys.add(stem)
        return sorted(keys)

    def _segments_of(self, key: str):
        """(path, index, stat) per existing segment of a series, index
        order."""
        out = []
        prefix = key + "."
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in names:
            if not (n.startswith(prefix) and n.endswith(_SEG_SUFFIX)):
                continue
            seg = n[len(prefix): -len(_SEG_SUFFIX)]
            if not seg.isdigit():
                continue
            path = os.path.join(self.root, n)
            try:
                out.append((path, int(seg), os.stat(path)))
            except OSError:
                continue
        out.sort(key=lambda s: s[1])
        return out

    # -- read path -----------------------------------------------------------
    def series(self) -> list[tuple[str, dict]]:
        """Every (metric, labels) series present on disk."""
        return [parse_series_key(k) for k in self._series_keys()]

    def _matching_keys(self, metric: str,
                       labels: Optional[dict]) -> list[str]:
        """Series whose metric matches and whose labels are a SUPERSET
        of the filter — ``labels={"q": "0.99"}`` merges that quantile
        across every replica."""
        want = {str(k): str(v) for k, v in (labels or {}).items()}
        out = []
        for key in self._series_keys():
            m, lb = parse_series_key(key)
            if m != metric:
                continue
            if all(lb.get(k) == v for k, v in want.items()):
                out.append(key)
        return out

    def query(self, metric: str, labels: Optional[dict] = None,
              since_s: Optional[float] = None,
              now: Optional[float] = None) -> list[tuple[float, float]]:
        """Merged (t, value) samples of every matching series, timestamp
        order, restricted to the trailing ``since_s`` look-back window.
        Torn tails and unparsable lines are skipped, never raised."""
        if now is None:
            now = time.time()
        cutoff = None if since_s is None else \
            now - float(since_s)  # lint: allow-wall-clock(epoch axis)
        out: list[tuple[float, float]] = []
        for key in self._matching_keys(metric, labels):
            for path, _idx, _stat in self._segments_of(key):
                out.extend(self._read_segment(path, cutoff))
        out.sort(key=lambda s: s[0])
        return out

    @staticmethod
    def _read_segment(path: str, cutoff: Optional[float]):
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return []
        lines = raw.split(b"\n")
        # A file not ending in newline ends in a torn write: the final
        # chunk is incomplete by the one-write-per-line contract — drop
        # it. (split leaves b"" as the last element when it DID end in
        # a newline.)
        lines = lines[:-1]
        out = []
        for ln in lines:
            if not ln:
                continue
            try:
                rec = json.loads(ln)
                t, v = float(rec["t"]), float(rec["v"])
            except (ValueError, KeyError, TypeError):
                continue  # torn or foreign line: skip, never fail a read
            if cutoff is not None and t < cutoff:
                continue
            out.append((t, v))
        return out

    def percentile(self, metric: str, q: float,
                   labels: Optional[dict] = None,
                   since_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """Nearest-rank percentile of the merged samples over the
        look-back window (None when the window is empty) — the same
        ``_pct`` the live windows and the report use."""
        vals = sorted(v for _, v in self.query(
            metric, labels, since_s=since_s, now=now
        ))
        return _pct(vals, q)

    def latest(self, metric: str, labels: Optional[dict] = None
               ) -> Optional[tuple[float, float]]:
        """The newest (t, value) across matching series, or None."""
        samples = self.query(metric, labels)
        return samples[-1] if samples else None

    def stats(self) -> dict:
        return {
            "appended": self.appended,
            "dropped": self.dropped,
            "dark": self._dark,
            "series": len(self._series_keys()),
        }
