"""Request-scoped distributed tracing for the serving path.

The serving telemetry so far is *aggregate*: window percentiles, SLO
alerts, batch occupancy. None of it can answer "what happened to THIS
request" — which batch it rode, how long it queued vs sat on device, or
why a client's observed latency disagrees with the server's
``serving_ms``. This module is the per-request causality layer:

- A **trace id** is minted at admission (or accepted from the caller —
  the ``X-Featurenet-Trace`` HTTP header, the propagation hook a fleet
  router uses to follow one request across a process hop) and echoed in
  the response. Ids are 16 hex chars; a caller-supplied id is accepted
  when it matches ``_ID_RE`` (≤64 chars of ``[A-Za-z0-9._-]``) and
  replaced with a minted one otherwise — a hostile header must not be
  able to inject arbitrary bytes into the event stream.
- The batcher stamps each ``PendingRequest`` with its ``TraceContext``
  and records ``request_admit`` / ``request_dispatch`` /
  ``request_done`` / ``request_reject`` events into the existing JSONL
  streams. One dispatch fans in N trace ids; the de-mux fans them back
  out, so the merged log reconstructs a per-request server-side
  timeline (``cli report --request <id>``).
- **Tail-biased sampling** bounds cardinality: events are *buffered* on
  the context and the keep/drop decision is made at completion, when
  the outcome is known — so rejections, errors, and SLO-breaching
  requests are ALWAYS kept while healthy traffic is downsampled to
  ``Config.trace_sample``. The rate decision is a pure hash of the
  trace id (``sampled``), so every host — and the future fleet router —
  agrees on it with no coordination.

Overhead discipline: with no event sink installed nothing is buffered
(one ``None`` check per hook, the obs layer's standing contract), and
the minted id costs one ``os.urandom`` read. The measured cost of the
sampled-on path is pinned in the bench gate (``trace_overhead_pct``),
so tracing can never silently tax the hot path. Telemetry is never
load-bearing: every write goes through the degrading event sink.

Stdlib-only, like the rest of the obs package.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import time
from typing import Optional

from featurenet_tpu.obs import events as _events

# The HTTP propagation header: accepted on the request, echoed on every
# response (200s, overload 503s, even 400s — the caller keyed its own
# bookkeeping off the id it sent).
TRACE_HEADER = "X-Featurenet-Trace"

_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# Outcomes a request_done event may carry. "ok" is downsampled by rate;
# "error" is always kept (tail bias).
OUTCOMES = ("ok", "error")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (64 random bits — collision-free at
    any realistic request volume, and cheap enough for the hot path)."""
    return os.urandom(8).hex()


def normalize_trace_id(raw: Optional[str]) -> str:
    """A usable trace id from caller input: the supplied id when it is
    well-formed (``_ID_RE``), a minted one otherwise (including None —
    the common no-header case)."""
    if raw and _ID_RE.match(raw):
        return raw
    return mint_trace_id()


def sampled(trace_id: str, rate: float) -> bool:
    """The deterministic rate decision: a pure hash of the trace id
    against ``rate``, so two processes (or two hosts, or the router and
    the replica) always agree on whether a given id is sampled — cross-
    host agreement is free, no coordination channel needed. Forced
    samples (rejects / errors / SLO breaches) bypass this entirely."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int.from_bytes(
        hashlib.sha256(trace_id.encode("utf-8")).digest()[:8], "big"
    )
    return h / float(1 << 64) < rate


class TraceContext:
    """One request's trace state: its id plus the buffered events the
    tail-biased sampler will flush (or drop) at completion. ``_events``
    is None when no sink was active at admission — the dark path
    allocates nothing beyond the context itself."""

    __slots__ = ("trace_id", "sample_rate", "_buffered", "_finished")

    def __init__(self, trace_id: str, sample_rate: float):
        self.trace_id = trace_id
        self.sample_rate = float(sample_rate)
        self._buffered: Optional[list[dict]] = (
            [] if _events.active() else None
        )
        self._finished = False


# Process-wide sampling counters for the /metrics exporter ("how much of
# my traffic is traced" is a scrape-able scaling signal). Reset with the
# run (obs.close_run), like every other piece of ambient obs state.
_counters = {"admitted": 0, "done": 0, "sampled": 0, "forced": 0,
             "rejected": 0}
_counters_lock = threading.Lock()

# Incident mode (obs.incidents): while an incident is open, EVERY
# request's timeline is kept — the bundle's events tail must hold the
# bad window's complete traces, not a rate-sampled subset. This is the
# tail-bias hook widened to everything; reset with the run like the
# counters (a leaked flag would silently un-sample-rate the next run).
_force_all = False


def set_force_all(on: bool) -> None:
    global _force_all
    _force_all = bool(on)


def force_all() -> bool:
    return _force_all


def counters() -> dict:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    global _force_all
    _force_all = False
    with _counters_lock:
        for k in _counters:
            _counters[k] = 0


def admit(trace_id: Optional[str] = None,
          sample_rate: float = 1.0) -> TraceContext:
    """Mint (or adopt) a trace context at the admission point and buffer
    its ``request_admit`` event. Called by the batcher's ``submit`` —
    the one place every serving request passes through."""
    ctx = TraceContext(normalize_trace_id(trace_id), sample_rate)
    with _counters_lock:
        _counters["admitted"] += 1
    if ctx._buffered is not None:
        ctx._buffered.append({
            "kind": "request_admit",
            "t": time.time(),
            "thread": threading.get_ident(),
        })
    return ctx


def dispatch(ctx: Optional[TraceContext], batch_seq: int, bucket: int,
             pad: int) -> None:
    """Buffer the ``request_dispatch`` event: this request left the
    queue on batch ``batch_seq``, padded into ``bucket``. The batch
    attribution is what ties N fanned-in trace ids to one
    ``serve_dispatch`` span (which carries the same ``batch_seq``)."""
    if ctx is None or ctx._buffered is None:
        return
    ctx._buffered.append({
        "kind": "request_dispatch",
        "t": time.time(),
        "batch_seq": int(batch_seq),
        "bucket": int(bucket),
        "pad": int(pad),
        "thread": threading.get_ident(),
    })


def _flush_buffered(ctx: TraceContext) -> None:
    """Emit the buffered admit/dispatch events with their ORIGINAL
    timestamps (the sampler decided late; the timeline must not lie
    about when things happened). Explicit per-kind emits so the
    telemetry lint can check each kind's required fields statically."""
    for rec in ctx._buffered or ():
        if rec["kind"] == "request_admit":
            _events.emit("request_admit", t=rec["t"], trace=ctx.trace_id,
                         thread=rec["thread"])
        elif rec["kind"] == "request_dispatch":
            _events.emit("request_dispatch", t=rec["t"],
                         trace=ctx.trace_id, batch_seq=rec["batch_seq"],
                         bucket=rec["bucket"], pad=rec["pad"],
                         thread=rec["thread"])
    ctx._buffered = []


def reject(ctx: Optional[TraceContext], queue_depth: int,
           limit: int) -> None:
    """An admission fast-reject: ALWAYS sampled (a rejection is exactly
    the request an operator goes looking for), flushed immediately —
    there is no later completion point to defer to."""
    if ctx is None or ctx._finished:
        return
    ctx._finished = True
    with _counters_lock:
        _counters["rejected"] += 1
        _counters["forced"] += 1
    if ctx._buffered is None:
        return
    _flush_buffered(ctx)
    _events.emit("request_reject", trace=ctx.trace_id,
                 queue_depth=int(queue_depth), limit=int(limit))


def done(ctx: Optional[TraceContext], queue_wait_ms: float,
         dispatch_ms: float, total_ms: float, outcome: str = "ok",
         slo_ms: Optional[float] = None) -> None:
    """Completion: decide the tail-biased sample and flush or drop the
    buffered timeline. Forced (always kept) when the outcome is an
    error or the request breached the serving SLO — the tail IS the
    point; healthy traffic falls to the deterministic rate decision."""
    if ctx is None or ctx._finished:
        return
    ctx._finished = True
    forced = _force_all or outcome != "ok" or (
        slo_ms is not None and total_ms > slo_ms
    )
    keep = forced or sampled(ctx.trace_id, ctx.sample_rate)
    with _counters_lock:
        _counters["done"] += 1
        if keep:
            _counters["sampled"] += 1
        if forced:
            _counters["forced"] += 1
    if not keep or ctx._buffered is None:
        ctx._buffered = None
        return
    _flush_buffered(ctx)
    _events.emit("request_done", trace=ctx.trace_id,
                 queue_wait_ms=round(float(queue_wait_ms), 3),
                 dispatch_ms=round(float(dispatch_ms), 3),
                 total_ms=round(float(total_ms), 3),
                 outcome=outcome,
                 forced=forced)
