"""Report-driven regression gates: a run (or bench round) judges itself.

The perf trajectory used to live only in human eyes reading BENCH_*.json
diffs; a regression surfaced a round late, if at all. A gate pins a
baseline — data-wait fraction, p99 serving latency, step time, restart
count, bench throughput — with a tolerance, and a completed run's report
is evaluated against it mechanically:

- ``cli report <run_dir> --gate baseline.json`` exits non-zero on any
  regression (CI-able: train, then gate the run's own telemetry).
- ``bench.py`` emits a pin-ready ``gate_summary`` each round and checks
  itself against the previously pinned round (``BENCH_baseline.json``).

Baseline JSON shape (``{"gates": {...}}`` wrapper optional)::

    {"gates": {
        "data_wait_fraction": {"value": 0.25, "tolerance": 0.10},
        "serving_p99_ms":     {"value": 12.0, "tolerance": 0.15},
        "restarts":           {"value": 0, "tolerance_abs": 1},
        "e2e_samples_per_sec": {"value": 9800, "direction": "min"}
    }}

``tolerance`` is relative (default 0.10), ``tolerance_abs`` absolute
(default 0 — the only meaningful slack for a zero baseline like restart
count); both may be given and add. ``direction`` says which way is a
regression: ``"max"`` = higher is worse (latencies, fractions, counts),
``"min"`` = lower is worse (throughputs). Unknown metrics default to
``"max"`` — pessimism beats silently waving a regression through. A
metric the baseline pins but the report lacks is a failure too
("missing"): a gate that can't see its metric must not pass.

Stdlib-only, like the rest of the report path.
"""

from __future__ import annotations

import json
from typing import Optional

DEFAULT_TOLERANCE = 0.10

# Which way is worse, per known metric. Everything extracted from a run
# report regresses upward; bench throughput/MFU regress downward.
DIRECTIONS = {
    "data_wait_fraction": "max",
    "step_ms": "max",
    "serving_p99_ms": "max",
    "serving_mean_ms": "max",
    "restarts": "max",
    "stalls": "max",
    "heartbeat_max_age_s": "max",
    "bad_lines": "max",
    # Cross-host data-wait spread (report.host_skew): a fat spread on a
    # lockstep mesh is free throughput — a widening one is a regression.
    "data_wait_spread": "max",
    # Live-SLO window percentiles (bench records the last window of its
    # e2e row — see bench._window_gate_fields). Queue depth regresses
    # DOWNWARD: a pipeline pinned at 0 is a starving device.
    "window_data_wait_p50_ms": "max",
    "window_data_wait_p99_ms": "max",
    "window_queue_depth_p50": "min",
    # bench summary keys (see bench_gate_values)
    "value": "min",
    "serving_inferences_per_sec_per_chip": "min",
    "mfu": "min",
    # Performance attribution (obs.perf): measured-cost MFU of the train
    # step and the serving program regress DOWNWARD like throughput —
    # they ARE throughput, restated against the device peak; the train
    # step's compiled peak-memory footprint regresses UPWARD (growing
    # HBM pressure eats the headroom the remaining speed rungs need).
    "mfu_train": "min",
    "serve_mfu": "min",
    "hbm_peak_train_bytes": "max",
    # Mixed-precision training rungs (Config.train_precision): the
    # bf16-master and fp16+loss-scaling rows regress like their fp32
    # siblings — throughput/MFU downward, compiled peak memory and
    # measurement spread upward.
    "train_sps_bf16_master": "min",
    "train_bf16_master_spread_pct": "max",
    "mfu_train_bf16_master": "min",
    "hbm_peak_train_bytes_bf16_master": "max",
    "train_sps_fp16_scaled": "min",
    "train_fp16_scaled_spread_pct": "max",
    "mfu_train_fp16_scaled": "min",
    "hbm_peak_train_bytes_fp16_scaled": "max",
    # Layout-specialized 3^3 conv stem (arch.conv_backend="fused33",
    # ops/conv33.py): the flagship measured under the tap-unrolled
    # lowering — a regression here is the specialization rotting
    # against XLA upgrades.
    "train_sps_fused33": "min",
    "train_fused33_spread_pct": "max",
    "e2e_samples_per_sec": "min",
    "e2e_pipelined_samples_per_sec": "min",
    "e2e_hbm_samples_per_sec": "min",
    "spread_pct": "max",
    "serving_spread_pct": "max",
    # Reduced-precision serving throughput (serve_packed_bf16 /
    # serve_packed_int8 — the serving rungs of the precision ladder,
    # each agreement-gated at the paper's 96.7%) and time-to-first-step
    # through the persistent executable cache: cold = fresh XLA compile,
    # warm = guarded cache load. Both TTFS keys regress UPWARD — a warm
    # start creeping back toward cold means the cache stopped serving
    # (rejects, fingerprint churn).
    "serving_bf16_inferences_per_sec_per_chip": "min",
    "serving_bf16_spread_pct": "max",
    "serve_mfu_bf16": "min",
    "serving_int8_inferences_per_sec_per_chip": "min",
    "serving_int8_spread_pct": "max",
    "ttfs_cold_s": "max",
    "ttfs_warm_s": "max",
    # Open-loop serving (serve.loadgen.bench_serving): sustained QPS and
    # batch occupancy regress DOWNWARD (the service keeping up / the
    # bucket ladder staying full), end-to-end latency percentiles and
    # overload rejections regress upward.
    "serve_qps_sustained": "min",
    "serve_p50_ms": "max",
    "serve_p99_ms": "max",
    "serve_occupancy": "min",
    "serve_rejected": "max",
    # Client-observed open-loop latency (loadgen's own clock): regresses
    # upward like the server-side percentiles; the p99 gap between the
    # two is queueing upstream of admission.
    "serve_client_p99_ms": "max",
    # Request-tracing tax (serve.loadgen.measure_trace_overhead):
    # sampled-on vs dark closed-loop rate through one warmed service.
    # Regresses UPWARD — tracing must never silently grow a hot-path
    # cost; the pin is what enforces "never load-bearing" as a measured
    # property rather than a docstring claim.
    "trace_overhead_pct": "max",
    # Model-quality telemetry tax (serve.loadgen.
    # measure_quality_overhead): quality-plane-on vs detached closed-
    # loop rate through one warmed service — per-request confidence
    # math, drift scoring, and the flight recorder's capture policy
    # must never silently grow a hot-path cost, same contract as
    # trace_overhead_pct.
    "quality_overhead_pct": "max",
    # Incident-plane tax (serve.loadgen.measure_incident_overhead):
    # closed-loop rate through a fully-traced service with an incident
    # manager armed (event tap installed, alert funnel watched, no
    # incident open) vs dark. Being ARMED must stay near-free — a
    # capture is alert-gated and runs on its own thread, but the tap
    # consult rides every emit, so its cost is pinned like
    # trace_overhead_pct.
    "incident_overhead_pct": "max",
    # Telemetry-collection tax (fleet.loadgen.bench_fleet): open-loop
    # fleet qps with the scraper collecting vs paused, same warm fleet.
    # Regresses UPWARD for the same reason as trace_overhead_pct —
    # "collection is never load-bearing" must be a measured property.
    "scrape_overhead_pct": "max",
    # One burn-query + scale-verdict evaluation wall (the router's
    # store-backed ``scale_state``): the control loop's decision latency
    # — PR-17's autoscaler acts on this, so it must stay cheap.
    "fleet_burn_verdict_ms": "max",
    # The acting control loop (fleet.replica.Autoscaler): actions taken
    # during the bench's steady-state fleet window. The bench fleet runs
    # a flat load, so ANY action is flapping — regresses upward from an
    # expected 0 (absolute slack below keeps an honest one-off legal).
    "fleet_scale_actions": "max",
    # Zero-downtime rollout pins (bench_fleet's self-rollout: swap the
    # live fleet to the SAME checkpoint): how long one replica's
    # verify+restore+flip takes, and the replay-canary agreement of the
    # candidate against the capture ring (self-rollout ⇒ ~1.0 —
    # regresses DOWNWARD toward the paper's 0.967 bar).
    "rollout_swap_ms": "max",
    "rollout_agreement": "min",
    # Scaling-efficiency gate (the MULTICHIP_r0*.json series made
    # self-policing): per-chip train throughput at each power-of-two
    # data-mesh shape (benchmark.measure_scaling) regresses DOWNWARD,
    # as does the retention ratio (largest shape's per-chip rate over
    # the single-chip rate — a lockstep mesh leaking throughput to the
    # slowest member shows up here before anyone reads a host table).
    "scaling_sps_per_chip_1x": "min",
    "scaling_sps_per_chip_2x": "min",
    "scaling_sps_per_chip_4x": "min",
    "scaling_sps_per_chip_8x": "min",
    "scaling_sps_per_chip_16x": "min",
    "scaling_sps_per_chip_32x": "min",
    "scaling_sps_per_chip_64x": "min",
    "scaling_efficiency": "min",
    # Serving fleet (featurenet_tpu.fleet, bench_fleet's row measured
    # THROUGH a mid-run replica kill): sustained router-level QPS
    # regresses downward, the fleet p99 upward, and dropped admitted
    # requests are pinned at a baseline of ZERO with no slack — the
    # whole point of the re-submit path is that replica loss never
    # loses admitted work.
    "fleet_qps_sustained": "min",
    "fleet_p99_ms": "max",
    "fleet_requests_dropped": "max",
    # Persistent-connection data plane (fleet.pool): router-side channel
    # reuse over the whole bench_fleet run, measured THROUGH the kill.
    # Regresses DOWNWARD — a ratio sliding toward 0 is the data plane
    # rotting back to connect-per-request (the PR-15 gap reopening).
    "fleet_conn_reuse_ratio": "min",
}


def report_gate_values(rep: dict) -> dict[str, float]:
    """The gateable scalars of a run report (``obs.report.build_report``).
    Only metrics the run actually recorded appear — a classify train run
    with no serving spans simply has no ``serving_p99_ms`` to gate."""
    vals: dict[str, float] = {}
    bd = rep.get("breakdown")
    if bd:
        vals["data_wait_fraction"] = bd["data_wait"]["fraction"]
    loop = rep.get("loop") or {}
    if loop.get("step_ms") is not None:
        vals["step_ms"] = loop["step_ms"]
    sv = rep.get("serving_latency_ms")
    if sv:
        vals["serving_p99_ms"] = sv["p99"]
        vals["serving_mean_ms"] = sv["mean"]
    sup = rep.get("supervisor")
    vals["restarts"] = float((sup or {}).get("restarts", 0))
    vals["stalls"] = float((sup or {}).get("stalls", 0))
    hb = rep.get("heartbeat")
    if hb and hb.get("max_age_s") is not None:
        vals["heartbeat_max_age_s"] = hb["max_age_s"]
    # Multi-host runs: the cross-host data-wait spread is gateable — a
    # lockstep mesh's global step time is its slowest host's, so a
    # widening spread is throughput leaking even when host 0 looks fine
    # (ROADMAP obs-next item).
    dwf = (rep.get("host_skew") or {}).get("data_wait_fraction")
    if dwf and dwf.get("spread") is not None:
        vals["data_wait_spread"] = dwf["spread"]
    # Performance attribution (obs.perf): the rolling MFU's p50 and the
    # train programs' compiled peak-memory footprint are gateable like
    # any throughput/latency scalar — an MFU regression fails --gate
    # exactly like a samples/sec regression. Both honest-absence: a CPU
    # run (unknown peak tier) records no mfu window, a degraded cost
    # capture no peak_bytes, and the keys simply stay out.
    perf = rep.get("perf") or {}
    mfu_row = perf.get("mfu")
    if isinstance(mfu_row, dict) and mfu_row.get("p50") is not None:
        vals["mfu"] = float(mfu_row["p50"])
    train_peaks = [
        row["peak_bytes"]
        for name, row in (perf.get("programs") or {}).items()
        if name in ("train_step", "multi_train_step", "hbm_train_step")
        and isinstance(row.get("peak_bytes"), (int, float))
    ]
    if train_peaks:
        vals["hbm_peak_train_bytes"] = float(max(train_peaks))
    # Serving fleet: the drained drop count is gateable from a run
    # report too — a fleet run dir judges its own zero-drop promise.
    fleet = rep.get("fleet") or {}
    if isinstance(fleet.get("dropped"), (int, float)):
        vals["fleet_requests_dropped"] = float(fleet["dropped"])
    vals["bad_lines"] = float(rep.get("bad_lines", 0))
    return vals


# Bench-summary keys worth pinning round over round (bench.py's output
# dict). The spread keys bound measurement QUALITY, not performance —
# they are pinned so a blown-up spread (a contaminated session quoting a
# lucky draw) is itself a gate failure, but bench gives them a generous
# absolute slack (bench.SPREAD_TOLERANCE_ABS) so honest noisy rounds
# still pass. The window_* keys are the live-SLO percentiles of the e2e
# row (bench._window_gate_fields), present only when the e2e cache is.
BENCH_GATE_KEYS = (
    "value",
    "serving_inferences_per_sec_per_chip",
    "mfu",
    "e2e_samples_per_sec",
    "e2e_pipelined_samples_per_sec",
    "e2e_hbm_samples_per_sec",
    "spread_pct",
    "serving_spread_pct",
    "serving_bf16_inferences_per_sec_per_chip",
    "serving_bf16_spread_pct",
    "serve_mfu_bf16",
    "serving_int8_inferences_per_sec_per_chip",
    "serving_int8_spread_pct",
    "ttfs_cold_s",
    "ttfs_warm_s",
    "mfu_train",
    "serve_mfu",
    "hbm_peak_train_bytes",
    "train_sps_bf16_master",
    "train_bf16_master_spread_pct",
    "mfu_train_bf16_master",
    "hbm_peak_train_bytes_bf16_master",
    "train_sps_fp16_scaled",
    "train_fp16_scaled_spread_pct",
    "mfu_train_fp16_scaled",
    "hbm_peak_train_bytes_fp16_scaled",
    "train_sps_fused33",
    "train_fused33_spread_pct",
    "window_data_wait_p50_ms",
    "window_data_wait_p99_ms",
    "window_queue_depth_p50",
    "serve_qps_sustained",
    "serve_p50_ms",
    "serve_p99_ms",
    "serve_client_p99_ms",
    "serve_occupancy",
    "serve_rejected",
    "trace_overhead_pct",
    "quality_overhead_pct",
    "incident_overhead_pct",
    # Scaling-efficiency gate: samples/sec per mesh shape plus the
    # cross-host data-wait spread of the 2-host probe run — present only
    # when the round could measure them (device count / probe success),
    # like the e2e block on a cache-less round.
    "scaling_sps_per_chip_1x",
    "scaling_sps_per_chip_2x",
    "scaling_sps_per_chip_4x",
    "scaling_sps_per_chip_8x",
    "scaling_sps_per_chip_16x",
    "scaling_sps_per_chip_32x",
    "scaling_sps_per_chip_64x",
    "scaling_efficiency",
    "data_wait_spread",
    "fleet_qps_sustained",
    "fleet_p99_ms",
    "fleet_requests_dropped",
    "fleet_conn_reuse_ratio",
    "scrape_overhead_pct",
    "fleet_burn_verdict_ms",
    "fleet_scale_actions",
    "rollout_swap_ms",
    "rollout_agreement",
)


def bench_gate_values(summary: dict) -> dict[str, float]:
    return {
        k: float(summary[k]) for k in BENCH_GATE_KEYS
        if isinstance(summary.get(k), (int, float))
    }


def make_baseline(values: dict[str, float],
                  tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Pin-ready baseline from current values (what bench emits as
    ``gate_summary`` and what an operator freezes after a good run)."""
    return {
        "gates": {
            name: {
                "value": v,
                "tolerance": tolerance,
                "direction": DIRECTIONS.get(name, "max"),
            }
            for name, v in sorted(values.items())
        }
    }


# Measurement-quality / near-zero-baseline pins that need ABSOLUTE
# slack on top of the relative tolerance: a relative tolerance on a
# near-zero baseline pins "never change", so honest run-to-run wiggle
# would fail the gate. One table, shared by bench.py's self-pin and the
# bench-history trend gate — the two judges must agree on what counts
# as noise. (Rationale per key lives with the bench harness; the values
# are in the pinned metric's own units.)
SPREAD_TOLERANCE_ABS = 5.0

NOISY_KEY_ABS_SLACK = {
    "spread_pct": SPREAD_TOLERANCE_ABS,
    "serving_spread_pct": SPREAD_TOLERANCE_ABS,
    "serving_int8_spread_pct": SPREAD_TOLERANCE_ABS,
    "ttfs_cold_s": 10.0,
    "ttfs_warm_s": 5.0,
    "mfu_train": 0.02,
    "serve_mfu": 0.02,
    "hbm_peak_train_bytes": 32.0 * 1024 * 1024,
    "train_bf16_master_spread_pct": SPREAD_TOLERANCE_ABS,
    "mfu_train_bf16_master": 0.02,
    "hbm_peak_train_bytes_bf16_master": 32.0 * 1024 * 1024,
    "train_fp16_scaled_spread_pct": SPREAD_TOLERANCE_ABS,
    "mfu_train_fp16_scaled": 0.02,
    "hbm_peak_train_bytes_fp16_scaled": 32.0 * 1024 * 1024,
    "train_fused33_spread_pct": SPREAD_TOLERANCE_ABS,
    "serving_bf16_spread_pct": SPREAD_TOLERANCE_ABS,
    "serve_mfu_bf16": 0.02,
    "window_data_wait_p50_ms": 1.0,
    "window_data_wait_p99_ms": 5.0,
    "window_queue_depth_p50": 1.0,
    "serve_p50_ms": 5.0,
    "serve_p99_ms": 15.0,
    "serve_client_p99_ms": 15.0,
    "serve_rejected": 16.0,
    "trace_overhead_pct": 10.0,
    # The quality tax rides the same closed-loop A/B as the trace tax
    # and inherits its run-to-run noise floor — same absolute room.
    "quality_overhead_pct": 10.0,
    # The incident tax rides the same A/B and noise floor too.
    "incident_overhead_pct": 10.0,
    "data_wait_spread": 0.1,
    "fleet_p99_ms": 25.0,
    "fleet_conn_reuse_ratio": 0.05,
    # Telemetry-collection tax: near zero by design (the scraper rides
    # the warm pool off the hot path) — same reasoning as
    # trace_overhead_pct, same room.
    "scrape_overhead_pct": 10.0,
    # One store query + verdict over a bench-sized store is
    # single-digit ms; relative tolerance there pins "never change".
    # The gate is for the control loop's decision latency growing to
    # something an autoscaler would feel.
    "fleet_burn_verdict_ms": 25.0,
    # Steady-state bench fleet expects ZERO autoscale actions — a
    # relative tolerance on 0 pins "never act"; one action of slack
    # keeps an honestly borderline round legal while a thrash (2+)
    # still fails.
    "fleet_scale_actions": 1.0,
    # One swap = checksum walk + Orbax restore + device-put + cast;
    # restore wall is filesystem-noisy at bench scale, so give it real
    # absolute room on top of the relative band.
    "rollout_swap_ms": 2000.0,
    # Self-rollout agreement is ~1.0 by construction; tiny absolute
    # room for a capture ring with a single borderline row.
    "rollout_agreement": 0.02,
}


def apply_abs_slack(baseline: dict) -> dict:
    """Stamp ``NOISY_KEY_ABS_SLACK`` onto a ``make_baseline`` result's
    pins (in place; returns it for chaining) — only keys actually
    pinned get slack."""
    for noisy, slack in NOISY_KEY_ABS_SLACK.items():
        pin = baseline.get("gates", {}).get(noisy)
        if pin is not None:
            pin["tolerance_abs"] = slack
    return baseline


def evaluate_gates(values: dict[str, float], baseline: dict) -> dict:
    """Judge ``values`` against a baseline spec. Returns
    ``{"ok": bool, "failed": [names], "gates": [per-gate records]}`` —
    ``ok`` only when every pinned metric is present and within its limit.
    """
    spec = baseline.get("gates", baseline)
    gates: list[dict] = []
    failed: list[str] = []
    for name in sorted(spec):
        b = spec[name]
        if not isinstance(b, dict):
            b = {"value": b}
        base = float(b["value"])
        tol = float(b.get("tolerance", DEFAULT_TOLERANCE))
        tol_abs = float(b.get("tolerance_abs", 0.0))
        direction = b.get("direction") or DIRECTIONS.get(name, "max")
        rec: dict = {
            "metric": name,
            "baseline": base,
            "tolerance": tol,
            "direction": direction,
        }
        if tol_abs:
            rec["tolerance_abs"] = tol_abs
        value = values.get(name)
        if value is None:
            rec.update(status="missing", value=None)
            failed.append(name)
            gates.append(rec)
            continue
        value = float(value)
        if direction == "min":
            limit = base * (1.0 - tol) - tol_abs
            ok = value >= limit - 1e-12
        else:
            limit = base * (1.0 + tol) + tol_abs
            ok = value <= limit + 1e-12
        rec.update(
            status="pass" if ok else "fail",
            value=value,
            limit=round(limit, 6),
        )
        if not ok:
            failed.append(name)
        gates.append(rec)
    return {"ok": not failed, "failed": failed, "gates": gates}


def load_baseline(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        baseline = json.load(fh)
    spec = baseline.get("gates", baseline)
    if not isinstance(spec, dict) or not spec:
        raise ValueError(
            f"baseline {path!r} pins no gates — expected "
            '{"gates": {"<metric>": {"value": ...}}} or a flat '
            "metric→value object"
        )
    return baseline


def format_gates(result: dict, baseline_path: Optional[str] = None) -> str:
    lines = []
    head = "gate: " + ("PASS" if result["ok"] else "FAIL")
    if baseline_path:
        head += f" (baseline {baseline_path})"
    lines.append(head)
    for g in result["gates"]:
        arrow = "<=" if g["direction"] == "max" else ">="
        if g["status"] == "missing":
            lines.append(
                f"  MISSING {g['metric']}: pinned at {g['baseline']} but "
                "absent from this report"
            )
        else:
            lines.append(
                f"  {'ok' if g['status'] == 'pass' else 'FAIL':<4} "
                f"{g['metric']:<36} {g['value']:>12.4g} {arrow} "
                f"{g['limit']:<12.4g} (baseline {g['baseline']:g}, "
                f"tol {g['tolerance'] * 100:g}%"
                + (f" + {g['tolerance_abs']:g}" if g.get("tolerance_abs")
                   else "")
                + ")"
            )
    return "\n".join(lines)
