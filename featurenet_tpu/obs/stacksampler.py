"""Host-side thread-stack sampler: N seconds of folded stacks, stdlib-only.

The perf layer (``obs.perf``) attributes *device* time — compiled-program
flops over wall against the chip peak. Nothing so far attributes *host*
Python time: the batcher's dispatcher thread, the fleet router's workers,
the scraper, the autoscaler, the tsdb writer all burn CPU that no
existing telemetry can localize. This module is the host-side
complement: a sampling profiler over ``sys._current_frames()`` that
needs no signal handlers, no native extension, and no cooperation from
the sampled threads.

``sample_stacks`` polls every thread's current frame at ``hz`` for
``duration_s`` and folds each observation into the standard
flamegraph-folded form::

    <thread name>;file.py:outermost;...;file.py:innermost <count>

Thread NAMES lead each stack (resolved via ``threading.enumerate`` each
tick, so late-spawned threads are attributed too) — "where did host CPU
go" is only actionable when the answer names ``serve-batcher`` or
``fleet-scale``, not an integer ident.

Overrun discipline: the sampler runs inside an incident capture with a
run to finish around it, so it carries a **hard wall-clock deadline**
(``max_wall_s``, default 2× the requested duration). A machine so loaded
that sampling itself lags — exactly when a profile is most interesting —
ends the loop at the deadline and keeps the partial profile, marked
``truncated``: a late answer beats none, and the sampler must never wedge
the capture thread it runs on.

Sampling another thread's frame is inherently racy (the GIL makes each
``_current_frames`` snapshot internally consistent, but a frame may be
mid-return); folding only (filename, name) pairs keeps every tick valid.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

DEFAULT_HZ = 50.0
DEFAULT_DURATION_S = 2.0


def _thread_names() -> dict[int, str]:
    """ident → name for every live thread (re-resolved per tick: threads
    spawned mid-profile still get named)."""
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


def _fold_frame(frame) -> str:
    """One thread's current stack as ``file:func;...`` outermost-first.
    Semicolons/spaces cannot occur in the segments (filenames are
    basenames, code names are identifiers), so the folded grammar stays
    parseable."""
    parts: list[str] = []
    f = frame
    while f is not None:
        code = f.f_code
        parts.append(
            f"{os.path.basename(code.co_filename)}:{code.co_name}"
        )
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


def sample_stacks(duration_s: float = DEFAULT_DURATION_S,
                  hz: float = DEFAULT_HZ,
                  max_wall_s: Optional[float] = None) -> dict:
    """Sample every thread's stack for ``duration_s`` at ``hz``; returns
    ``{"folded": {stack: count}, "samples": n, "ticks": t,
    "duration_s": wall, "truncated": bool}``. The calling thread itself
    is excluded (profiling the profiler is noise). ``max_wall_s`` is the
    hard overrun deadline (default ``2 * duration_s``): a loop that
    cannot keep cadence stops there with the partial profile kept."""
    duration_s = max(0.0, float(duration_s))
    interval = 1.0 / max(1.0, float(hz))
    if max_wall_s is None:
        max_wall_s = 2.0 * duration_s
    self_ident = threading.get_ident()
    folded: dict[str, int] = {}
    ticks = samples = 0
    truncated = False
    t0 = time.monotonic()
    deadline = t0 + max(float(max_wall_s), interval)
    end = t0 + duration_s
    while True:
        now = time.monotonic()
        if now >= end:
            break
        if now >= deadline:
            # Overrun: the host is too loaded for the requested cadence
            # (which is itself evidence). Keep what we have.
            truncated = True
            break
        names = _thread_names()
        # One internally-consistent snapshot of every thread's frame.
        frames = sys._current_frames()
        ticks += 1
        for ident, frame in frames.items():
            if ident == self_ident:
                continue
            name = names.get(ident, f"thread-{ident}")
            stack = f"{name};{_fold_frame(frame)}"
            folded[stack] = folded.get(stack, 0) + 1
            samples += 1
        del frames  # drop the frame refs before sleeping
        time.sleep(interval)
    return {
        "folded": folded,
        "samples": samples,
        "ticks": ticks,
        "duration_s": round(time.monotonic() - t0, 3),
        "truncated": truncated,
    }


def render_folded(profile: dict) -> str:
    """The profile's ``folded`` dict as standard folded-stack text (one
    ``stack count`` line, count-descending) — the form every flamegraph
    tool ingests, and what an incident bundle stores."""
    folded = profile.get("folded") or {}
    lines = [f"{stack} {count}" for stack, count in
             sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def parse_folded(text: str) -> dict[str, int]:
    """Inverse of ``render_folded`` (tolerant: malformed lines are
    skipped, a torn tail must not kill a post-mortem render)."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def thread_totals(folded: dict[str, int]) -> dict[str, int]:
    """Per-thread sample totals from a folded dict (the first segment of
    every stack is the thread name) — the one-line summary ``cli
    incident show`` leads with."""
    out: dict[str, int] = {}
    for stack, count in folded.items():
        name = stack.split(";", 1)[0]
        out[name] = out.get(name, 0) + count
    return out
