"""Process-wide JSONL event sink + run manifest (the obs layer's spine).

Event wire format: one JSON object per line in ``<run_dir>/events.jsonl``,
every line carrying ``t`` (epoch seconds) and ``ev`` (the event type —
``span`` / ``gauge`` / ``metrics`` / ``warning`` / ``heartbeat`` /
``supervisor`` / ``loop_start`` / ``loop_end`` / ``run_start`` /
``run_end``). The field is ``ev``, not ``kind``, so ``MetricLogger``
records — which already carry a ``kind`` of their own — route through
unmodified.

Multi-host layout: process 0 writes ``events.jsonl`` (the original
single-file name, so every pre-existing log keeps reading) and is the sole
owner of ``run.json``; every other process writes its own
``events.<process_index>.jsonl`` (``events_filename``). One file per
writer-host means no cross-host interleaving at all; the report layer
(``obs.report.load_events``) discovers every stream, tags each record
with its ``process_index``, and merges by timestamp.

Concurrency: one lock per sink serializes threads; the file is opened
``O_APPEND`` and each event is a single ``os.write`` of one complete line,
so independent *processes* sharing a file (the supervisor and its
supervised child, or a restarted child appending to the same run — both
host-0 residents) interleave whole lines, never fragments, even through a
shared filesystem client that honors O_APPEND. The manifest (``run.json``)
is written once per run directory — a respawned child finds it present and
only appends a ``run_start`` event, keeping the original start time while
making every restart visible in the timeline.

The module-level sink is what the instrumentation hooks (``emit`` /
``gauge`` / ``spans.span``) consult; when none is installed every hook
returns after one ``None`` check — the contract that keeps an
un-instrumented run's dispatch path at zero overhead and zero file I/O.

The sink is deliberately PROCESS-WIDE and sticky: once ``init_run``
installs it, everything the process does afterwards — including later
Trainers constructed with ``run_dir=None`` (a recalibration pass, an
eval over the same weights, a benchmark rerun) — logs into the active
run until ``close_run()`` or an ``init_run`` naming a different
directory. That is the point: ambient work belongs to the run that is
in flight. A process that interleaves genuinely unrelated runs must
``init_run`` each one (which swaps the sink) or ``close_run()`` between
them.
"""

from __future__ import annotations

import errno
import json
import os
import sys
import threading
import time
from typing import Any, Optional

from featurenet_tpu import faults

MANIFEST_FILENAME = "run.json"
EVENTS_FILENAME = "events.jsonl"


def events_filename(process_index: Optional[int] = 0) -> str:
    """Per-host event stream name. Host 0 keeps the legacy single-file
    name (old run dirs and old readers stay valid); host i>0 gets
    ``events.<i>.jsonl``."""
    if not process_index:
        return EVENTS_FILENAME
    return f"events.{int(process_index)}.jsonl"


def _device_topology() -> dict:
    """Best-effort JAX device/process topology for the manifest. Lazy and
    guarded: the report CLI (and the supervisor process) must be able to
    use this module without initializing a backend."""
    try:
        import jax

        return {
            "version": jax.__version__,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "devices": [
                {
                    "id": d.id,
                    "process_index": d.process_index,
                    "platform": d.platform,
                    "device_kind": d.device_kind,
                }
                for d in jax.devices()
            ],
        }
    except Exception as e:  # no jax / no backend: manifest still valid
        return {"error": str(e)}


def run_manifest(run_dir: str, config: Optional[dict] = None,
                 extra: Optional[dict] = None) -> dict:
    import datetime
    import socket

    m: dict[str, Any] = {
        "run_dir": os.path.abspath(run_dir),
        "start_unix": time.time(),
        "start_time": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "pid": os.getpid(),
        "hostname": socket.gethostname(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "config": config,
        "jax": _device_topology(),
    }
    if extra:
        m.update(extra)
    return m


class EventSink:
    """Append-only JSONL writer for one run directory.

    Standalone-instantiable (the supervisor opens its own sink into the
    child's run_dir from a different process); training code normally goes
    through the module-level singleton installed by ``init_run``.
    """

    def __init__(self, run_dir: str, filename: str = EVENTS_FILENAME):
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.path = os.path.join(self.run_dir, filename)
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._emits = 0
        # Per-kind emit counts for the /metrics exporter: the sink sees
        # every event this process records, so counting here folds the
        # whole telemetry surface (compiles, cache verdicts, overloads)
        # into scrape-able counters with no second bookkeeping layer.
        self._kind_counts: dict[str, int] = {}
        # Raw fd, O_APPEND: every emit below is exactly one os.write of one
        # complete line. POSIX append semantics make each such write land
        # at the (atomically advanced) end of file, so concurrent writers
        # with independent fds — the supervisor and its child, a restarted
        # child, obs.warn from two processes — can interleave lines but
        # never shear one. A buffered file object would re-split the bytes
        # at its own buffer boundary and void that guarantee.
        self._fd: Optional[int] = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )

    def emit(self, ev: str, **fields) -> None:
        """Write one event line. A ``t`` in ``fields`` overrides the
        auto-stamp (spans pass their start time so trace viewers see the
        interval where it began, not where it ended). Every line carries
        the emitting pid: several processes may share one stream
        (supervisor + child, restarted children), and the Chrome trace
        export groups spans by it."""
        record = {"t": fields.pop("t", None) or time.time(), "ev": ev,
                  "pid": self._pid}
        record.update(fields)
        data = (json.dumps(record, default=str) + "\n").encode("utf-8")
        # The event tap (obs.incidents): consulted OUTSIDE the write
        # lock below would reorder against the write; consulted here —
        # before the lock — it sees every event this process emits (even
        # after the sink goes dark: the incident plane has its own
        # go-dark state and must still see alert transitions). Guarded:
        # a tap must never raise into an emit site.
        tap = _tap
        if tap is not None:
            try:
                tap(ev, record)
            except Exception:
                pass
        with self._lock:
            if self._fd is None:
                return
            self._emits += 1
            self._kind_counts[ev] = self._kind_counts.get(ev, 0) + 1
            # Telemetry is never load-bearing: a write that fails at the
            # OS level (ENOSPC, quota, a yanked network filesystem) must
            # not crash training. Degrade to a no-op sink with exactly one
            # stderr warning — the run keeps training dark, like a run
            # that never had a run_dir. Exercised by the ``sink_enospc``
            # injection site.
            try:
                if faults.maybe_fail("sink_enospc", emit=self._emits):
                    raise OSError(errno.ENOSPC, "injected ENOSPC",
                                  self.path)
                # Single unbuffered write per line (see __init__); no
                # flush needed, so a crashed run's log is complete to the
                # crash. Regular-file appends complete in one write() in
                # practice; if the kernel ever returns short (ENOSPC
                # boundary, quota), the atomicity of THIS line is already
                # lost, so finishing the tail beats silently gluing it
                # onto the next record.
                view = memoryview(data)
                while view:
                    view = view[os.write(self._fd, view):]
            except OSError as e:
                fd, self._fd = self._fd, None
                try:
                    os.close(fd)
                except OSError:
                    pass
                print(json.dumps({
                    "sink_error": f"event sink write failed "
                    f"({type(e).__name__}: {e}); telemetry for this "
                    "process goes dark, training continues",
                    "path": self.path,
                }), file=sys.stderr)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


# --- module-level (process-wide) sink ----------------------------------------

_sink: Optional[EventSink] = None
_install_lock = threading.Lock()

# The module-level event tap: ONE subscriber sees every event any sink
# in this process emits (the incident manager's subscription point —
# alert transitions, gate regressions, replica losses — with no
# per-callsite wiring). Deliberately a single slot, not a listener
# list: the obs layer has exactly one downstream consumer, and a second
# would deserve its own design pass.
_tap = None


def set_tap(fn) -> None:
    """Install (or, with None, remove) the process-wide event tap. The
    tap is called as ``fn(ev, record)`` from the EMITTING thread, after
    the record is built but before the write — it must be cheap and must
    not raise (the emit site guards anyway)."""
    global _tap
    _tap = fn


def init_run(run_dir: str, config: Optional[dict] = None,
             extra: Optional[dict] = None,
             process_index: Optional[int] = None) -> EventSink:
    """Install the process-wide sink for ``run_dir`` and ensure ``run.json``.

    Idempotent per directory: re-initializing the same run_dir (a second
    Trainer in one process, a respawned supervised child) keeps appending
    to the existing log; a different run_dir closes the old sink and opens
    the new one. The manifest is written only if absent so restarts keep
    the run's original start time; every call appends a ``run_start``
    event, which is how the report reconstructs the restart timeline.

    ``process_index``: which per-host stream this process owns
    (``events_filename``). None = ask the JAX topology (0 when no backend
    is reachable, so single-process callers never pay for the question).
    Host 0 is the sole owner of ``run.json`` — on a shared filesystem N
    hosts racing one manifest write would be the only cross-host file
    race in the layer, so it is simply not run anywhere else.
    """
    global _sink
    if process_index is None:
        process_index = _device_topology().get("process_index", 0) or 0
    # The live-SLO layer rides the sink: a (default-rule) window
    # aggregator exists whenever a run is active, so serving/ingest
    # processes get rolling windows without a Trainer in the process.
    # Function-level import: windows imports this module at its top.
    from featurenet_tpu.obs import windows as _windows

    with _install_lock:
        target = os.path.abspath(run_dir)
        filename = events_filename(process_index)
        path = os.path.join(target, filename)
        if _sink is None or _sink.path != path:
            if _sink is not None:
                # Switching runs: the old run's final window cycle goes
                # into the OLD stream, then the aggregator is dropped —
                # run B's first summary must come from run B's samples
                # (and run B's rules), not run A's ring buffers. The
                # tracing counters reset with it: run B's /metrics must
                # not report run A's sampled-request totals (the fresh
                # sink already zeroes the per-kind counts beside them).
                from featurenet_tpu.obs import tracing as _tracing

                _windows.flush()
                _windows.uninstall()
                _tracing.reset_counters()
                _sink.close()
            _sink = EventSink(target, filename=filename)
        _windows.ensure_default()
        if process_index == 0:
            manifest_path = os.path.join(target, MANIFEST_FILENAME)
            if not os.path.exists(manifest_path):
                tmp = manifest_path + ".tmp"  # atomic: never half a manifest
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(run_manifest(run_dir, config, extra), fh,
                              indent=1, default=str)
                os.replace(tmp, manifest_path)
        _sink.emit("run_start", process_index=process_index)
        return _sink


def active() -> bool:
    return _sink is not None


def kind_counts() -> dict[str, int]:
    """Per-kind emit counts of the active sink (empty when dark) — the
    /metrics exporter's source for compiles / cache verdicts / serving
    events without a second counting layer anywhere."""
    s = _sink
    if s is None:
        return {}
    with s._lock:
        return dict(s._kind_counts)


def emit(ev: str, **fields) -> None:
    """Emit to the process-wide sink; no-op (one None check) when none."""
    s = _sink
    if s is None:
        return
    s.emit(ev, **fields)


def gauge(name: str, value, **fields) -> None:
    """Point-in-time measurement (queue depth, batch-gen seconds, …)."""
    s = _sink
    if s is None:
        return
    s.emit("gauge", name=name, value=value, **fields)


def warn(name: str, msg: str, **fields) -> None:
    """One-line JSON warning to stderr (the pre-obs contract every ad-hoc
    ``*_warning`` print site followed — kept so operators and tests that
    grep stderr see the same shape) AND, when a run is active, a
    ``warning`` event in the run log."""
    print(json.dumps({name: msg, **fields}), file=sys.stderr)
    s = _sink
    if s is not None:
        s.emit("warning", name=name, msg=msg, **fields)


def close_run() -> None:
    global _sink
    from featurenet_tpu.obs import tracing as _tracing
    from featurenet_tpu.obs import windows as _windows

    # Flush pending window summaries (and their alert evaluation) while
    # the sink can still write them, then drop the aggregator with the
    # sink — obs state must never leak across runs in one process. The
    # tracing counters are ambient obs state like the aggregator: run
    # B's /metrics must not report run A's sampled-request counts.
    if _sink is not None:
        _windows.flush()
    _windows.uninstall()
    _tracing.reset_counters()
    with _install_lock:
        if _sink is not None:
            _sink.close()
            _sink = None
