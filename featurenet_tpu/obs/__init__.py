"""Run-scoped observability: one directory captures a whole run.

The reference printed loss to stdout and nothing else (SURVEY.md §5); this
rebuild's telemetry had grown piecemeal — ``MetricLogger`` JSON lines, a
bare heartbeat mtime, ad-hoc ``*_warning`` prints — with no single artifact
answering "where did this run's wall-clock go, did the input pipeline
starve the device, and why did the supervisor restart it?" (BENCH_r05
failed on a backend outage with no run-side record of the stall shape.)

Setting ``Config.run_dir`` (CLI ``--run-dir``) makes every layer write into
one run directory:

- ``run.json``    — manifest: config, device topology, process index,
                    start time (``events.init_run``; host 0 only).
- ``events.jsonl``— host 0's append-only, thread-safe event log: timing
                    spans, gauges, metrics, warnings, heartbeats,
                    supervisor restarts (``events.EventSink``).
- ``events.<i>.jsonl`` — every other host's stream (multi-process runs;
                    ``events.events_filename``). One file per writer, so
                    nothing cross-host ever interleaves; the report layer
                    merges them by timestamp and tags each record with
                    its ``process_index``.

Post-hoc, ``python -m featurenet_tpu.cli report <run_dir>`` folds the
merged log into a step-time breakdown (data-wait vs device vs eval vs
checkpoint), prefetch-queue-depth percentiles, heartbeat-age max, a
restart/stall timeline, a serving-latency histogram, and — for multi-host
runs — a per-host breakdown with cross-host skew stats (``report.py``);
``--follow`` live-tails the same streams incrementally while the run is
hot; ``--trace`` exports the spans as a Chrome ``trace.json`` with one
track per host (``spans.chrome_trace``); ``--validate`` lints the event
schema; ``--gate baseline.json`` evaluates regression gates (``gates.py``)
and exits non-zero on a regression.

Live SLOs ride the same stream (``windows.py`` + ``alerts.py``): an
in-process rolling-window aggregator keeps the last N steps' step-time /
data-wait / queue-depth / heartbeat-age / serving-latency percentiles and
periodically emits ``window_summary`` events; declarative alert rules
(``Config.alert_rules`` / ``--alert-rules``, with sane defaults) fire
structured ``alert`` events when a window goes bad — rendered live by
``report --follow`` and post-hoc in the report's SLO section, and never
load-bearing.

With no run_dir configured every hook is a no-op behind a single ``None``
check — no file I/O, no timestamps, no measurable train-step overhead.
This package imports only the stdlib (plus the equally dependency-free
``featurenet_tpu.faults`` chaos registry), so any layer may import it
freely.
"""

from featurenet_tpu.obs.events import (
    EventSink,
    active,
    close_run,
    emit,
    events_filename,
    gauge,
    init_run,
    warn,
)
from featurenet_tpu.obs.spans import chrome_trace, span
from featurenet_tpu.obs.windows import flush as flush_windows
from featurenet_tpu.obs.windows import observe

__all__ = [
    "EventSink",
    "active",
    "chrome_trace",
    "close_run",
    "emit",
    "events_filename",
    "flush_windows",
    "gauge",
    "init_run",
    "observe",
    "span",
    "warn",
]
