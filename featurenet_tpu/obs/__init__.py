"""Run-scoped observability: one directory captures a whole run.

The reference printed loss to stdout and nothing else (SURVEY.md §5); this
rebuild's telemetry had grown piecemeal — ``MetricLogger`` JSON lines, a
bare heartbeat mtime, ad-hoc ``*_warning`` prints — with no single artifact
answering "where did this run's wall-clock go, did the input pipeline
starve the device, and why did the supervisor restart it?" (BENCH_r05
failed on a backend outage with no run-side record of the stall shape.)

Setting ``Config.run_dir`` (CLI ``--run-dir``) makes every layer write into
one run directory:

- ``run.json``    — manifest: config, device topology, process index,
                    start time (``events.init_run``).
- ``events.jsonl``— append-only, thread-safe, process-shared event log:
                    timing spans, gauges, metrics, warnings, heartbeats,
                    supervisor restarts (``events.EventSink``).

Post-hoc, ``python -m featurenet_tpu.cli report <run_dir>`` folds the event
log into a step-time breakdown (data-wait vs device vs eval vs checkpoint),
prefetch-queue-depth percentiles, heartbeat-age max, a restart/stall
timeline, and a serving-latency histogram (``report.py``); ``--trace``
exports the spans as a Chrome ``trace.json`` (``spans.chrome_trace``).

With no run_dir configured every hook is a no-op behind a single ``None``
check — no file I/O, no timestamps, no measurable train-step overhead.
This package imports only the stdlib, so any layer may import it freely.
"""

from featurenet_tpu.obs.events import (
    EventSink,
    active,
    close_run,
    emit,
    gauge,
    init_run,
    warn,
)
from featurenet_tpu.obs.spans import chrome_trace, span

__all__ = [
    "EventSink",
    "active",
    "chrome_trace",
    "close_run",
    "emit",
    "gauge",
    "init_run",
    "span",
    "warn",
]
