"""Streaming rolling-window aggregation: the live half of the obs layer.

The report (``obs.report``) judges a run after the fact; production
degradation has to be seen *while it happens*. This module maintains
in-process ring buffers — the last N samples / T seconds — of the step
loop's health signals (``alerts.WINDOW_METRICS``: step time, data-wait,
prefetch queue depth, heartbeat age, serving latency, and the perf
layer's per-dispatch MFU / achieved-bandwidth fractions), computes their
p50/p95/p99 online, and periodically emits one ``window_summary`` event
per metric. Every sample is a host-side float the instrumentation
already had in hand (a span's ``perf_counter`` duration, a queue length)
— the aggregator never touches a device value, so watching the run costs
no host sync.

On each emission cycle the configured alert rules (``obs.alerts``) are
evaluated against the windows and violated rules fire structured
``alert`` events. Telemetry is never load-bearing: everything here only
*writes* events, through a sink that already degrades to a no-op on
write failure.

Like the event sink, the aggregator is process-wide and optional:
``observe``/``observe_span`` with none installed are one module
attribute load and a ``None`` check — the un-instrumented dispatch path
pays nothing. ``events.init_run`` installs a default-rule aggregator
alongside the sink; the Trainer replaces it with one built from
``Config.alert_rules``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import events as _events
# ONE percentile implementation for the live and post-hoc views: a
# formula change in the report must change the windows with it, never
# silently diverge the two (the schema-drift class the linter polices).
from featurenet_tpu.obs.report import _pct

# Span names that feed a window directly: (metric, unit scale, divisor
# field). Span durations are seconds; the windows speak milliseconds.
# The divisor keeps samples PER-STEP comparable: a fused dispatch's
# data_wait span covers `take` steps at once, and without the
# normalization data_wait_fraction would read k× too high on pipelined
# runs (step_ms is per-step by construction).
SPAN_METRICS = {
    "data_wait": ("data_wait_ms", 1e3, "take"),
    "infer_batch": ("serving_ms", 1e3, None),
}

DEFAULT_WINDOW = 128       # samples per ring buffer (last N steps)
DEFAULT_MAX_AGE_S = 300.0  # and never older than this (last T seconds)
DEFAULT_EMIT_EVERY_S = 5.0


class RollingWindow:
    """Ring buffer of (timestamp, value) bounded by count AND age."""

    __slots__ = ("maxlen", "max_age_s", "_samples")

    def __init__(self, maxlen: int = DEFAULT_WINDOW,
                 max_age_s: Optional[float] = DEFAULT_MAX_AGE_S):
        self.maxlen = maxlen
        self.max_age_s = max_age_s
        self._samples: deque = deque(maxlen=maxlen)

    def add(self, value: float, now: float) -> None:
        self._samples.append((now, float(value)))

    def values(self, now: float) -> list[float]:
        if self.max_age_s is not None:
            cutoff = now - self.max_age_s
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
        return [v for _, v in self._samples]

    def summary(self, now: float) -> Optional[dict]:
        vals = sorted(self.values(now))
        if not vals:
            return None
        return {
            "n": len(vals),
            "p50": round(_pct(vals, 50), 4),
            "p95": round(_pct(vals, 95), 4),
            "p99": round(_pct(vals, 99), 4),
            "mean": round(sum(vals) / len(vals), 4),
            "max": round(vals[-1], 4),
        }

    def total(self, now: float) -> float:
        return sum(self.values(now))


class WindowAggregator:
    """Rolling windows for every ``alerts.WINDOW_METRICS`` metric, with
    periodic ``window_summary`` emission and alert-rule evaluation.

    ``emit_every_s`` bounds both the event volume and the alert rate: a
    cycle emits one summary per *dirty* (newly-observed) metric, stamps
    them all with one monotonically increasing ``seq``, then evaluates
    the process-scope rules — a violated rule fires one ``alert`` event
    carrying that ``seq`` as its ``window``. ``flush()`` forces a final
    cycle (the loop end / ``close_run`` hook), so even a run shorter than
    the period lands its summaries.
    """

    def __init__(self, rules: Optional[list] = None,
                 window: int = DEFAULT_WINDOW,
                 max_age_s: Optional[float] = DEFAULT_MAX_AGE_S,
                 emit_every_s: float = DEFAULT_EMIT_EVERY_S):
        self.rules = list(_alerts.DEFAULT_RULES) if rules is None else \
            list(rules)
        self.emit_every_s = emit_every_s
        self._win = {
            m: RollingWindow(window, max_age_s)
            for m in _alerts.WINDOW_METRICS
        }
        self._dirty: set[str] = set()
        self._lock = threading.Lock()
        self._seq = 0
        self._last_emit = time.perf_counter()
        # Hysteresis state per rule metric: True between a fired alert and
        # its paired resolve — a violation lasting N cycles is ONE alert,
        # not N (carried-over SLO follow-on).
        self._alert_active: dict[str, bool] = {}

    def observe(self, metric: str, value: float) -> None:
        win = self._win.get(metric)
        if win is None:
            return  # unknown metric: ignore, never crash instrumentation
        now = time.perf_counter()
        with self._lock:
            win.add(value, now)
            self._dirty.add(metric)
            if now - self._last_emit >= self.emit_every_s:
                self._emit_locked(now)

    def flush(self) -> None:
        with self._lock:
            self._emit_locked(time.perf_counter())

    # -- internals (call with self._lock held) ------------------------------
    def _emit_locked(self, now: float) -> None:
        if not self._dirty:
            return
        self._last_emit = now
        self._seq += 1
        for metric in sorted(self._dirty):
            s = self._win[metric].summary(now)
            if s is None:
                continue
            _events.emit("window_summary", metric=metric, n=s["n"],
                         p50=s["p50"], p95=s["p95"], p99=s["p99"],
                         mean=s["mean"], max=s["max"], seq=self._seq)
        self._dirty.clear()
        for rule in self.rules:
            if rule.scope != "process":
                continue  # cross-host rules are the report's to judge
            value = self.rule_value(rule.metric, now)
            if value is None:
                continue
            active = self._alert_active.get(rule.metric, False)
            if rule.violated(value) and not active:
                # Crossing INTO violation: one fire, then silence until
                # the paired resolve below.
                _alerts.fire(rule, value, self._seq, state="fire")
                self._alert_active[rule.metric] = True
            elif not rule.violated(value) and active:
                _alerts.fire(rule, value, self._seq, state="resolve")
                self._alert_active[rule.metric] = False

    def active_alerts(self) -> list[str]:
        """Rule metrics currently in the fired-but-unresolved state — the
        serving drain gate reads this after its final ``flush()``."""
        with self._lock:
            return sorted(
                m for m, on in self._alert_active.items() if on
            )

    def snapshot(self) -> dict:
        """Current summary per metric with samples (the same numbers a
        ``window_summary`` emission would carry) — the live read the
        ``/metrics`` exporter scrapes, so external monitors and the SLO
        alerts judge the SAME windows."""
        now = time.perf_counter()
        with self._lock:
            out = {}
            for metric, win in self._win.items():
                s = win.summary(now)
                if s is not None:
                    out[metric] = s
            return out

    def samples(self, metric: str) -> list[tuple[float, float]]:
        """Raw (timestamp, value) samples of one window, age-pruned.
        Timestamps are ``perf_counter`` readings (the aggregator's
        clock), so callers comparing against "now" must use
        ``perf_counter`` too — this is the router's store-less burn
        fallback, not the durable epoch axis the tsdb keeps."""
        with self._lock:
            win = self._win.get(metric)
            if win is None:
                return []
            win.values(time.perf_counter())  # prune by age in place
            return list(win._samples)

    @property
    def seq(self) -> int:
        """The latest emission sequence number (0 = none yet)."""
        with self._lock:
            return self._seq

    def rule_value(self, metric: str, now: float) -> Optional[float]:
        """Resolve a rule metric against the current windows: a derived
        metric, or ``<window>_<stat>`` percentile lookup. None when the
        backing window(s) have no samples yet."""
        if metric == "data_wait_fraction":
            steps = self._win["step_ms"].total(now)
            if steps <= 0:
                return None
            return self._win["data_wait_ms"].total(now) / steps
        if metric == "step_p99_ratio":
            vals = sorted(self._win["step_ms"].values(now))
            p50 = _pct(vals, 50)
            if not p50:
                return None
            return _pct(vals, 99) / p50
        if metric == "heartbeat_age_s":
            vals = self._win["heartbeat_age_s"].values(now)
            return max(vals) if vals else None
        if metric == "queue_depth":
            return _pct(sorted(self._win["queue_depth"].values(now)), 50)
        if metric == "serving_p99_ms":
            return _pct(sorted(self._win["serving_ms"].values(now)), 99)
        if metric == "mfu":
            # Median, not max: one lucky fused dispatch must not resolve
            # a sustained-utilization alert.
            return _pct(sorted(self._win["mfu"].values(now)), 50)
        base, _, stat = metric.rpartition("_")
        win = self._win.get(base)
        if win is not None and stat in ("p50", "p95", "p99", "max", "mean"):
            s = win.summary(now)
            return None if s is None else s[stat]
        return None


# --- module-level (process-wide) aggregator ----------------------------------

_agg: Optional[WindowAggregator] = None


def install(agg: Optional[WindowAggregator]) -> None:
    global _agg
    _agg = agg


def uninstall() -> None:
    global _agg
    _agg = None


def active() -> bool:
    return _agg is not None


def ensure_default() -> None:
    """Install a default-rule aggregator if none exists (``init_run``'s
    hook, so ``cli infer --run-dir`` gets serving-latency windows without
    any Trainer in the process)."""
    global _agg
    if _agg is None:
        _agg = WindowAggregator()


def observe(metric: str, value: float) -> None:
    """Feed one sample; no-op (one None check) when no aggregator."""
    agg = _agg
    if agg is None:
        return
    agg.observe(metric, value)


def observe_span(name: str, dur_s: float,
                 fields: Optional[dict] = None) -> None:
    """Span-exit hook (``obs.spans``): route the spans that ARE window
    metrics (``SPAN_METRICS``) into their ring buffers, normalized by
    the span's divisor field (a fused dispatch's data_wait covers
    ``take`` steps — the sample must be per-step)."""
    agg = _agg
    if agg is None:
        return
    m = SPAN_METRICS.get(name)
    if m is None:
        return
    value = dur_s * m[1]
    if m[2] is not None and fields:
        div = fields.get(m[2])
        if isinstance(div, (int, float)) and div > 1:
            value /= div
    agg.observe(m[0], value)


def flush() -> None:
    agg = _agg
    if agg is not None:
        agg.flush()


def active_alerts() -> list[str]:
    """Currently-unresolved alert metrics of the installed aggregator;
    empty when none is installed (nothing watched = nothing active)."""
    agg = _agg
    if agg is None:
        return []
    return agg.active_alerts()


def snapshot() -> dict:
    """Live window summaries of the installed aggregator (empty when
    none) — the ``/metrics`` exporter's source."""
    agg = _agg
    if agg is None:
        return {}
    return agg.snapshot()


def samples(metric: str) -> list[tuple[float, float]]:
    """Raw (perf_counter, value) samples of one window of the installed
    aggregator (empty when none) — the burn-rate fallback for a router
    running without a time-series store."""
    agg = _agg
    if agg is None:
        return []
    return agg.samples(metric)


def last_seq() -> Optional[int]:
    """The installed aggregator's latest emission seq (None when none is
    installed; 0 before the first emission) — ``/healthz`` surfaces it
    so a monitor can tell a fresh server from one whose windows moved."""
    agg = _agg
    if agg is None:
        return None
    return agg.seq
