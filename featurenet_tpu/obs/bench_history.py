"""Cross-round bench trajectory: every ``BENCH_r*.json`` in one table.

The bench artifacts are one-file-per-round; reading the trajectory
means diffing JSON by hand, and a skipped round (r05's TPU outage) just
*vanishes* from any ad-hoc comparison. ``cli bench-history`` folds the
whole series into one table — throughput / MFU / serving / open-loop
serve pins per round — and renders skipped or unparseable rounds with
their STRUCTURED reason (the ``{"skipped": true, "reason": ...}``
record the probe hardening writes) instead of dropping them: an outage
is part of the trajectory, not a gap in it.

Stdlib-only, like the rest of the report path — the history must render
on a machine with no backend.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

from featurenet_tpu.obs import gates as _gates

_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")

# (artifact key, column header, format) — the columns worth reading
# round-over-round. Keys absent from a round render as "—" (older
# schemas simply had fewer fields). The fleet_* block is the serving-
# fleet trajectory (PR 14's pins plus PR 15's pooled-path reuse ratio)
# reading alongside sps/MFU, so a fleet regression is visible in the
# same table as a training one.
_COLUMNS = (
    ("value", "sps/chip", "{:.0f}"),
    ("mfu", "mfu", "{:.2f}"),
    ("mfu_train", "mfu_meas", "{:.2f}"),
    ("serving_inferences_per_sec_per_chip", "serve/chip", "{:.0f}"),
    ("serve_qps_sustained", "qps_open", "{:.0f}"),
    ("serve_p99_ms", "p99_ms", "{:.1f}"),
    ("ttfs_warm_s", "ttfs_w", "{:.1f}"),
    ("trace_overhead_pct", "trace_%", "{:.1f}"),
    ("quality_overhead_pct", "qual_%", "{:.1f}"),
    ("fleet_qps_sustained", "qps_fleet", "{:.0f}"),
    ("fleet_p99_ms", "fl_p99", "{:.1f}"),
    ("fleet_requests_dropped", "fl_drop", "{:.0f}"),
    ("fleet_conn_reuse_ratio", "fl_reuse", "{:.2f}"),
    ("scrape_overhead_pct", "scrape_%", "{:.1f}"),
    ("fleet_burn_verdict_ms", "burn_ms", "{:.1f}"),
    # The acting control loop + rollout pins (the autoscale/rollout
    # trajectory: actions taken under flat load — expected 0 — plus the
    # hot-swap wall and the self-rollout's replay-canary agreement).
    ("fleet_scale_actions", "scale_act", "{:.0f}"),
    ("rollout_swap_ms", "swap_ms", "{:.0f}"),
    ("rollout_agreement", "roll_agr", "{:.3f}"),
)


def load_rounds(bench_dir: str = ".") -> list[dict]:
    """Every ``BENCH_r<N>.json`` in ``bench_dir``, round-ordered, each
    folded to ``{"round", "status", "reason"?, <column keys>...}``.
    Three statuses: ``ok`` (a parsed measurement), ``skipped`` (the
    round recorded its own structured reason), ``unparseable`` (the
    artifact carries no parsed record at all — rc and the driver's
    wrapper are the only evidence, e.g. the pre-hardening r05)."""
    rows: list[dict] = []
    try:
        names = os.listdir(bench_dir)
    except OSError:
        return rows
    found = [(m, name) for name in names
             if (m := _ROUND_RE.match(name))]
    # Numeric round order, not filename order: BENCH_r10.json must not
    # sort before BENCH_r2.json (the regex accepts unpadded numbers).
    found.sort(key=lambda mn: int(mn[0].group(1)))
    for m, name in found:
        row: dict = {"round": f"r{int(m.group(1)):02d}"}
        try:
            with open(os.path.join(bench_dir, name),
                      encoding="utf-8") as fh:
                art = json.load(fh)
        except (OSError, ValueError) as e:
            row.update(status="unparseable",
                       reason=f"artifact unreadable: {e}")
            rows.append(row)
            continue
        # Driver wrapper ({"n", "rc", "parsed", ...}) or a bare bench
        # record — accept both so a hand-saved round still renders. A
        # top-level non-dict (a corrupted write that still parses as
        # JSON) is an unparseable round, not a crash.
        if not isinstance(art, dict):
            row.update(status="unparseable",
                       reason=f"artifact is {type(art).__name__} JSON, "
                              "not a bench record")
            rows.append(row)
            continue
        parsed = art.get("parsed") if "parsed" in art else art
        if not isinstance(parsed, dict):
            row.update(
                status="unparseable",
                reason=f"no parseable bench record (driver rc="
                       f"{art.get('rc')})",
            )
        elif parsed.get("skipped"):
            row.update(status="skipped",
                       reason=str(parsed.get("reason")))
            if parsed.get("error"):
                row["error"] = str(parsed["error"])[:200]
        else:
            row["status"] = "ok"
            for key, _, _ in _COLUMNS:
                if isinstance(parsed.get(key), (int, float)):
                    row[key] = parsed[key]
            # The FULL pinned-key set, for the trend gate below — the
            # table renders _COLUMNS, the gate judges every gate key
            # the round measured. Underscore key: not a column.
            row["_gate_values"] = _gates.bench_gate_values(parsed)
            gate = parsed.get("gate")
            if isinstance(gate, dict) and "ok" in gate:
                row["gate_ok"] = bool(gate["ok"])
                if gate.get("failed"):
                    row["gate_failed"] = list(gate["failed"])
        rows.append(row)
    return rows


def trend_gate(rows: list[dict],
               tolerance: float = _gates.DEFAULT_TOLERANCE) -> dict:
    """The round-over-round regression gate: judge the LATEST parseable
    round against the PREVIOUS one on the pinned bench keys, using the
    previous round's values as an ad-hoc baseline (same tolerance +
    noisy-key absolute slack as bench.py's self-pin). This is what lets
    CI gate a bench trajectory with no ``BENCH_baseline.json`` checked
    in — the history IS the baseline.

    Only keys present in BOTH rounds are judged: a conditional
    measurement block (the e2e cache, a device-count-gated scaling
    probe) legitimately comes and goes; a key the previous round never
    measured is not a regression, it is noted in ``dropped``/``gained``.
    Returns ``{"ok", "failed", "gates", "baseline_round",
    "candidate_round", ...}``; fewer than two parseable rounds is a
    trivially-ok gate with a ``note`` (nothing to trend ≠ a failure)."""
    ok_rows = [r for r in rows
               if r.get("status") == "ok" and r.get("_gate_values")]
    if len(ok_rows) < 2:
        return {
            "ok": True, "failed": [], "gates": [],
            "note": "fewer than two parseable rounds — nothing to trend",
        }
    prev, latest = ok_rows[-2], ok_rows[-1]
    prev_vals = dict(prev["_gate_values"])
    latest_vals = dict(latest["_gate_values"])
    shared = {k: v for k, v in prev_vals.items() if k in latest_vals}
    baseline = _gates.apply_abs_slack(
        _gates.make_baseline(shared, tolerance=tolerance)
    )
    result = _gates.evaluate_gates(latest_vals, baseline)
    result["baseline_round"] = prev["round"]
    result["candidate_round"] = latest["round"]
    result["dropped"] = sorted(set(prev_vals) - set(latest_vals))
    result["gained"] = sorted(set(latest_vals) - set(prev_vals))
    return result


def format_trend_gate(result: dict) -> str:
    if result.get("note"):
        return f"trend gate: ok ({result['note']})"
    head = (
        f"trend gate ({result['candidate_round']} vs "
        f"{result['baseline_round']}): "
        + ("PASS" if result["ok"] else "FAIL")
    )
    lines = [head]
    for g in result["gates"]:
        if g["status"] == "pass":
            continue
        lines.append(
            f"  FAIL {g['metric']:<36} {g['value']:>12.4g} vs limit "
            f"{g['limit']:g} (prev {g['baseline']:g})"
        )
    for key, label in (("dropped", "no longer measured"),
                       ("gained", "newly measured")):
        if result.get(key):
            lines.append(f"  note: {label}: {', '.join(result[key])}")
    return "\n".join(lines)


def format_history(rows: list[dict],
                   bench_dir: Optional[str] = None) -> str:
    """One table across rounds; skipped/unparseable rounds keep their
    line (reason in place of numbers) so the trajectory reads complete."""
    if not rows:
        return (
            f"bench-history: no BENCH_r*.json artifacts"
            + (f" in {bench_dir!r}" if bench_dir else "")
        )
    head = f"{'round':<6} {'status':<8}" + "".join(
        f" {hdr:>10}" for _, hdr, _ in _COLUMNS
    ) + "  gate"
    lines = [head]
    for row in rows:
        if row["status"] != "ok":
            lines.append(
                f"{row['round']:<6} {row['status']:<8} "
                f"{row.get('reason')}"
            )
            continue
        cells = []
        for key, _, fmt in _COLUMNS:
            v = row.get(key)
            cells.append(
                f" {fmt.format(v) if v is not None else '—':>10}"
            )
        gate = ("—" if "gate_ok" not in row
                else "ok" if row["gate_ok"]
                else "FAIL " + ",".join(row.get("gate_failed", [])))
        lines.append(
            f"{row['round']:<6} {row['status']:<8}" + "".join(cells)
            + f"  {gate}"
        )
    return "\n".join(lines)
