"""Benchmark: 64³-voxel training throughput, samples/sec/chip (BASELINE.json).

Runs the pod64 flagship config's compiled train step on all visible devices
(one real TPU chip under the driver) and prints ONE JSON line:

    {"metric": "...", "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

``vs_baseline``: BASELINE.json publishes no reference throughput (the paper
reports none — SURVEY.md §6); the north-star denominator is "single-V100
samples/sec" which cannot be measured here. We use a documented, conservative
stand-in: 330 samples/sec for FeatureNet-64³ on a V100 (fp32 cuDNN, batch 96 —
derived in BASELINE.md; flagged as estimated). vs_baseline = measured / 330.

Method: jit the full train step (fwd+bwd+optimizer+BN) at global batch 128,
warm up, then *slope timing*: wall (1 step + loss transfer) and (N+1 steps +
loss transfer); per-step time = (t_long - t_short)/N. The final scalar
transfer is the sync point — on this environment's tunneled TPU backend,
``block_until_ready`` returns before device execution completes, so only a
device→host readback is an honest wall; the slope subtracts the constant
round-trip latency from the measurement.
"""

from __future__ import annotations

import json
import time

import numpy as np

V100_SAMPLES_PER_SEC_EST = 330.0  # documented estimate, see BASELINE.md
# Per-chip batch: XLA pads the batch dim to multiples of 128 (measured —
# batch 96 and 128 take the same 53 ms step), so bench at the multiple;
# this is also the pod64 preset's training batch.
BATCH = 128
WARMUP, MEASURE = 5, 20


def main() -> None:
    import jax

    from featurenet_tpu.config import get_config
    from featurenet_tpu.data.synthetic import WIRE_KEYS, generate_batch, to_wire
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.parallel.mesh import (
        batch_shardings,
        make_mesh,
        replicated,
        state_shardings,
    )
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, make_train_step

    n_chips = len(jax.devices())
    mesh = make_mesh()  # all devices on 'data'
    cfg = get_config("pod64")
    # Per-chip batch stays BATCH regardless of chip count (weak scaling).
    global_batch = BATCH * mesh.shape["data"]

    model = FeatureNet(arch=cfg.arch)
    tx = make_optimizer(cfg)

    def init_fn(rng):
        import jax.numpy as jnp

        sample = jnp.zeros((global_batch, 64, 64, 64, 1), jnp.float32)
        return create_state(model, tx, sample, rng)

    abstract = jax.eval_shape(init_fn, jax.random.key(0))
    st_sh = state_shardings(abstract, mesh)
    state = jax.jit(init_fn, out_shardings=st_sh)(jax.random.key(0))

    # The real classify wire format: bit-packed voxels, no per-voxel target,
    # unpacked on device inside the compiled step.
    b_sh = batch_shardings(mesh, keys=WIRE_KEYS["classify"])
    step = jax.jit(
        make_train_step(model, "classify", packed=True),
        in_shardings=(st_sh, b_sh, replicated(mesh)),
        out_shardings=(st_sh, replicated(mesh)),
        donate_argnums=(0,),
    )

    host = to_wire(
        generate_batch(np.random.default_rng(0), global_batch, 64), "classify"
    )
    batch = jax.device_put(host, b_sh)
    rng = jax.device_put(jax.random.key(1), replicated(mesh))

    for _ in range(WARMUP):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # drain the pipe

    def walled(k: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])  # device→host readback = honest sync
        return time.perf_counter() - t0

    t_short = walled(1)
    t_long = walled(1 + MEASURE)
    per_step = (t_long - t_short) / MEASURE
    sps = global_batch / per_step
    sps_chip = sps / n_chips
    print(json.dumps({
        "metric": "featurenet64_train_throughput",
        "value": round(sps_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps_chip / V100_SAMPLES_PER_SEC_EST, 3),
    }))


if __name__ == "__main__":
    main()
