"""Benchmark: 64³-voxel training throughput, samples/sec/chip (BASELINE.json).

Driver entry point: runs the flagship config's (sprint64 — see main())
compiled train step on all visible devices (one real TPU chip under the
driver) and prints ONE JSON line:

    {"metric": "...", "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

``vs_baseline``: BASELINE.json publishes no reference throughput (the paper
reports none — SURVEY.md §6); the north-star denominator is "single-V100
samples/sec" which cannot be measured here. We use a documented, conservative
stand-in: 330 samples/sec for FeatureNet-64³ on a V100 (fp32 cuDNN, batch 96 —
derived in BASELINE.md; flagged as estimated). vs_baseline = measured / 330.

The MFU fields (analytic matmul FLOPs from ``ops/flops.py`` over the v5e
197 TF/s bf16 peak) make "distance from ceiling" checkable from this artifact
alone. Measurement core: ``featurenet_tpu.benchmark.measure_train_step``
(slope-timed; see its docstring); ``featurenet_tpu.ops.bench_arch`` sweeps
architecture variants with the same core.

The artifact is always one parseable JSON line: a backend probe runs in a
subprocess first, and an unreachable TPU yields a structured
``{"skipped": true, "backend": "cpu_fallback", "error": ...}`` record
instead of the raw JaxRuntimeError traceback BENCH_r05 died with. Each
successful round also emits a pin-ready ``gate_summary`` and judges itself
against the previously pinned round (``BENCH_baseline.json``,
``featurenet_tpu.obs.gates``) — the perf trajectory polices itself.
"""

from __future__ import annotations

import json

# The 24x1000 64^3 packed cache (built by `cli export-data`/`build-cache`);
# when present, bench.py also reports END-TO-END wall-clock training rate
# (host feed -> dispatch -> readback) unpipelined vs k-step pipelined.
E2E_CACHE = ".data/cls64_cache"
E2E_K = 8

# Independent slope measurements per model: the headline is the best slope,
# the artifact carries the spread. One slope through this environment's
# tunneled backend showed ±13% under host load (round-2 verdict weak #1);
# best-of-5 with in-artifact spread makes the artifact number the quotable
# one instead of a lucky/unlucky single draw.
REPEATS = 5

# Pinned gate baseline for round-over-round self-policing (obs.gates):
# when present, this round's summary is judged against it before the pin
# is refreshed with this round's numbers.
GATE_BASELINE = "BENCH_baseline.json"
GATE_TOLERANCE = 0.15  # slope spread through the tunnel runs ~3-7%
# The spread gates (measurement QUALITY, not performance) get an absolute
# slack on top: a 3.8% → 7% spread is an honest noisy session, not a
# regression — but a blown-up spread (a contaminated session quoting a
# lucky draw) should still fail the pin. The per-key table lives in
# obs.gates (NOISY_KEY_ABS_SLACK) so the bench-history trend gate and
# this harness judge noise identically.
SPREAD_TOLERANCE_ABS = 5.0  # == obs.gates.SPREAD_TOLERANCE_ABS


def _window_gate_fields(run_dir: str) -> dict:
    """Live-SLO window percentiles of the e2e row, as flat gate-summary
    fields. The plain (host-streamed) e2e measurement runs with an
    ambient run dir (see _measure_round), so the Trainer's own dispatch
    path feeds the rolling windows; the LAST summary per metric is the
    sustained steady state. Empty dict when the run produced no windows —
    the gate keys simply stay absent, like the e2e block on a cache-less
    round."""
    try:
        from featurenet_tpu.obs.report import load_events

        events, _ = load_events(run_dir)
    except (OSError, FileNotFoundError):
        return {}
    last: dict = {}
    for e in events:
        if e.get("ev") == "window_summary" and e.get("metric"):
            last[e["metric"]] = e
    out = {}
    dw = last.get("data_wait_ms")
    if dw:
        out["window_data_wait_p50_ms"] = dw.get("p50")
        out["window_data_wait_p99_ms"] = dw.get("p99")
    qd = last.get("queue_depth")
    if qd:
        out["window_queue_depth_p50"] = qd.get("p50")
    return out


# Signatures of the backend DYING UNDER the measurement (the BENCH_r05
# outage shape: the probe passed, then jax.devices() raised inside
# measure_train_step when the lease lapsed mid-round). Matched against
# the formatted traceback so the artifact can say "the backend was lost"
# instead of the generic "something raised" — the two reasons route to
# different operators (infra vs bench code).
_BACKEND_LOSS_SIGNATURES = (
    "Unable to initialize backend",
    "UNAVAILABLE",
    "TPU backend setup/compile error",
    "JaxRuntimeError",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    "Socket closed",
)


def _is_backend_loss(error_text: str) -> bool:
    """True when a mid-measurement exception reads as the accelerator
    (or its tunnel) going away, rather than a bug in the measurement."""
    return any(sig in error_text for sig in _BACKEND_LOSS_SIGNATURES)


# The probe child's whole job is to die informatively: it catches its OWN
# backend-init failure (make_c_api_client raising JaxRuntimeError during
# plugin init — the BENCH_r05 outage shape) and reports it as one JSON
# line instead of a traceback, so the parent never has to scrape stderr
# to stay parseable. BaseException on purpose: some plugin-init failures
# raise SystemExit-adjacent types, and anything the child can still
# format beats a raw abort.
_PROBE_SRC = """\
import json
try:
    import jax
    print(json.dumps({"platform": jax.devices()[0].platform}))
except BaseException as e:
    print(json.dumps(
        {"probe_error": (type(e).__name__ + ": " + str(e))[:1500]}
    ))
"""


def _probe_backend() -> tuple[str, str | None]:
    """Ask — in a THROWAWAY subprocess — whether the default JAX backend
    comes up. In-process probing is unusable: a failed backend init
    poisons jax's cached backend state, and the BENCH_r05 outage showed
    the failure mode (a raw JaxRuntimeError traceback mid-run, an
    unparseable artifact). The child answers in JSON either way (see
    ``_PROBE_SRC``); a child that died too hard to answer — fatal abort,
    signal, hang — degrades to its stderr tail. Returns
    ``(platform, None)`` or ``("", error_detail)``; the caller turns the
    latter into the structured ``{"skipped": true, ...}`` record, never
    an unhandled traceback."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=300,
        )
    except Exception as e:  # timeout, spawn failure
        return "", str(e)
    # Parse the child's JSON verdict (last parseable line: plugin noise
    # may precede it on stdout).
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("platform"):
            return str(rec["platform"]), None
        if isinstance(rec, dict) and "probe_error" in rec:
            return "", str(rec["probe_error"])
    tail = (r.stderr or r.stdout or "").strip()
    return "", tail[-1500:] or (
        f"probe subprocess exited {r.returncode} with no output"
    )


def main() -> None:
    import os

    # Contract self-check preamble (featurenet_tpu.analysis): the round
    # measures the package's own dispatch path, so a violated cross-
    # cutting contract — a typo'd fault site, an emit missing its schema
    # fields, an unannotated hot-loop host sync — fails the round with a
    # structured record (the same self-policing shape as the gate check
    # below) instead of producing a number built on a broken invariant.
    # Stdlib-only, runs before any jax import. Reproduce locally with:
    #   python -m featurenet_tpu.cli lint
    try:
        from featurenet_tpu.analysis import run_lint

        findings = run_lint()
    except Exception as e:  # the linter must never mask the measurement
        findings = []
        print(json.dumps({"lint_error": repr(e)[:500]}))
    if findings:
        print(json.dumps({
            "metric": "featurenet64_train_throughput",
            "bench_schema": 2,
            "skipped": True,
            "reason": "contract_violation",
            "lint": {
                "findings": len(findings),
                "first": f"{findings[0].location()}: "
                         f"[{findings[0].rule}/{findings[0].check}] "
                         f"{findings[0].msg}",
            },
        }))
        return

    # Probe the backend BEFORE any in-process jax import: when the TPU is
    # unreachable (lease lapse, tunnel outage — BENCH_r05's rc=1 traceback
    # tail) the round must still end in one parseable JSON line, not a
    # stack trace. No silent CPU re-run of the full protocol either: a 64³
    # batch-256 train step on this host's CPU is hours, and the number
    # would be meaningless next to TPU rounds — record the outage and the
    # fallback marker instead.
    platform, probe_err = _probe_backend()
    if not platform or platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"  # never retry the dead plugin
        print(json.dumps({
            "metric": "featurenet64_train_throughput",
            "bench_schema": 2,
            "skipped": True,
            "reason": ("tpu_backend_unavailable" if probe_err
                       else "no_accelerator_platform"),
            "backend": "cpu_fallback",
            "error": probe_err,
            "load_avg_1m": float(os.getloadavg()[0]),
        }))
        return
    try:
        out = _measure_round(platform)
    except Exception:
        # The probe can't rule out a MID-measurement outage (r05's actual
        # failure shape: the backend died between rows — jax.devices()
        # raising inside measure_train_step AFTER the probe passed). The
        # artifact must still be one parseable line carrying the
        # evidence, with the outage named as an outage ("backend_lost")
        # rather than the generic measurement error.
        import traceback

        tb = traceback.format_exc()
        print(json.dumps({
            "metric": "featurenet64_train_throughput",
            "bench_schema": 2,
            "skipped": True,
            "reason": ("backend_lost" if _is_backend_loss(tb)
                       else "measurement_error"),
            "backend": platform,
            "error": tb[-1500:],
            "load_avg_1m": float(os.getloadavg()[0]),
        }))
        return
    print(json.dumps(out))


def _measure_round(platform: str) -> dict:
    import os
    import time

    from featurenet_tpu.benchmark import (
        V100_SAMPLES_PER_SEC_EST,
        measure_e2e,
        measure_host_spread,
        measure_inference,
        measure_scaling,
        measure_train_step,
        measure_ttfs,
    )
    from featurenet_tpu.config import get_config
    from featurenet_tpu.obs import gates as obs_gates

    # Bounded idle-wait: a loaded host contaminates slope timings (round-3
    # profiler shipped a 10x bad reading under contention). Wait up to 2
    # minutes for the 1-minute loadavg to drop before measuring; record
    # both loadavgs in-artifact either way.
    load_at_invoke = float(os.getloadavg()[0])
    deadline = time.monotonic() + 120.0
    while os.getloadavg()[0] > 0.9 and time.monotonic() < deadline:
        time.sleep(5.0)

    # Flagship = sprint64 (round 4): warp64's 7³ stride-4 stem shrunk to
    # 5³ (coverage still complete, 5 > stride) — the round-3 profile's
    # named next lever, now validated: 99.98% held-out (4,799/4,800) at
    # the full 8k budget on the 24×1000 benchmark (one validation run —
    # warp64, at 99.92% over three runs, rides along as a secondary field
    # with the paper arch so rounds stay comparable; BASELINE.md round 4).
    cfg = get_config("sprint64")
    flag = measure_train_step(
        cfg, batch_per_chip=cfg.global_batch, repeats=REPEATS
    )
    # Mixed-precision training rung (ROADMAP item 2): the flagship
    # measured under the bf16-master policy (fp32 master weights in the
    # optimizer, bf16 working copy + bf16 gradient storage inside the
    # step — train/precision.py), same converged-slope protocol in the
    # same session so the fp32 row above is the honest denominator. The
    # row's own measured-cost MFU / compiled peak-HBM ride along so the
    # perf table attributes WHERE the delta came from.
    bf16 = measure_train_step(
        get_config("sprint64", train_precision="bf16_master"),
        batch_per_chip=cfg.global_batch, repeats=REPEATS,
    )
    # fp16+loss-scaling arm (ISSUE 12): the same master/working split at
    # float16 with dynamic loss scaling compiled into the step — the
    # rung that matters on backends where fp16 is the fast path. Same
    # session, same protocol, so the fp32 headline is the denominator.
    fp16 = measure_train_step(
        get_config("sprint64", train_precision="fp16_scaled"),
        batch_per_chip=cfg.global_batch, repeats=REPEATS,
    )
    # Layout-specialized 3^3 conv stem (ops/conv33.py, the roofline's
    # memory-bound lever): the flagship arch with its stride-1 3^3
    # blocks lowered as tap-unrolled channels-last matmuls instead of
    # XLA's generic conv. CPU numerics are pinned in tests; this row is
    # what TPU round r06 pins (vs_xla is the payoff measurement).
    import dataclasses as _dc

    fused33 = measure_train_step(
        _dc.replace(
            cfg, arch=_dc.replace(cfg.arch, conv_backend="fused33")
        ).validate(),
        batch_per_chip=cfg.global_batch, repeats=REPEATS,
    )
    wcfg = get_config("warp64")
    warp = measure_train_step(
        wcfg, batch_per_chip=wcfg.global_batch, repeats=REPEATS
    )
    paper = measure_train_step(get_config("pod64"), repeats=REPEATS)
    serving = measure_inference(cfg, repeats=REPEATS)
    # Reduced-precision serving rungs, identical converged-slope
    # protocol in the same session so the fp32 headline is the honest
    # denominator for both: bf16 (serve_packed_bf16 — the working-copy
    # cast compiled into the forward; serving is the traffic-dominant
    # program under the million-user north star, and this is its first
    # measured sub-fp32 rung with an agreement gate) and int8
    # (serve_packed_int8, per-channel weight-quantized).
    serving_bf16 = measure_inference(cfg, repeats=REPEATS, precision="bf16")
    serving_int8 = measure_inference(cfg, repeats=REPEATS, precision="int8")
    # Time-to-first-step through the persistent executable cache: cold
    # compiles and populates a throwaway cache, warm rebuilds through it.
    # warm_source records whether the guarded load actually served
    # ("cache") or degraded to a fresh compile ("fresh") — both are
    # honest artifacts.
    ttfs = measure_ttfs(cfg)
    # Open-loop serving (featurenet_tpu.serve): Poisson arrivals through
    # the continuous batcher + bucketed AOT executables — the number a
    # real request stream sustains, vs the closed-loop packed-batch
    # headline above that no traffic pattern can reach. Offered load =
    # BENCH_LOAD_FRACTION of this session's measured closed-loop rate
    # (deep enough to fill the big buckets, far from saturation), capped
    # where a Python-thread generator stops being open-loop.
    from featurenet_tpu.serve.loadgen import (
        BENCH_LOAD_FRACTION,
        BENCH_QPS_CAP,
        bench_serving,
    )

    serve_row = bench_serving(
        cfg,
        qps=min(BENCH_QPS_CAP,
                BENCH_LOAD_FRACTION
                * serving["inferences_per_sec_per_chip"]),
        n_requests=512,
    )
    # Request-tracing tax (obs.tracing): closed-loop rate through one
    # warmed service with the sampler fully on vs dark, same session.
    # Pinned (max) so tracing can never silently grow a hot-path cost;
    # a failure degrades to an absent key with the error in-artifact,
    # like the scaling rows — the headline numbers are already paid for.
    from featurenet_tpu.serve.loadgen import measure_trace_overhead

    trace_row: dict = {}
    try:
        trace_row = measure_trace_overhead(cfg)
    except Exception as e:
        trace_row = {"trace_overhead_error": repr(e)[:500]}
    # Model-quality telemetry tax (obs.quality + serve.recorder):
    # closed-loop rate with the per-request confidence/drift math and
    # the flight recorder's capture policy attached vs detached, same
    # session. Pinned (max) under the same "telemetry is never
    # load-bearing" contract as the trace row; a failure degrades to an
    # absent key with the error in-artifact.
    from featurenet_tpu.serve.loadgen import measure_quality_overhead

    quality_row: dict = {}
    try:
        quality_row = measure_quality_overhead(cfg)
    except Exception as e:
        quality_row = {"quality_overhead_error": repr(e)[:500]}
    # Incident-plane tax (obs.incidents): closed-loop rate through one
    # fully-traced warmed service with an incident manager armed (the
    # event tap installed, alert funnel watched, no incident open) vs
    # dark. Pinned (max) under the same "telemetry is never
    # load-bearing" contract; a failure degrades to an absent key with
    # the error in-artifact.
    from featurenet_tpu.serve.loadgen import measure_incident_overhead

    incident_row: dict = {}
    try:
        incident_row = measure_incident_overhead(cfg)
    except Exception as e:
        incident_row = {"incident_overhead_error": repr(e)[:500]}
    # Serving-fleet robustness row (featurenet_tpu.fleet.loadgen): a
    # 2-replica CPU fleet (replicas forced onto JAX_PLATFORMS=cpu —
    # this row pins the ROUTER layer, deliberately independent of
    # accelerator health) under open-loop load with one replica
    # SIGKILLed a third of the way in. fleet_qps_sustained must hold
    # through the loss and fleet_requests_dropped is pinned at ZERO;
    # a failure degrades to an absent key with the error in-artifact.
    # The row now measures the POOLED data plane (PR 15): every hop is
    # keep-alive, and fleet_conn_reuse_ratio is pinned (min) so the
    # plane can never silently rot back to connect-per-request.
    # The acting control loop + rollout plane ride the same fleet:
    # fleet_scale_actions (autoscaler moves under handled load — pinned
    # ~0, the flap-damping evidence), rollout_swap_ms (live hot-swap of
    # one replica back onto its own checkpoint), and rollout_agreement
    # (the swapped replica's capture ring replayed against that
    # checkpoint — pinned min ≈ 1.0).
    fleet_row: dict = {}
    try:
        from featurenet_tpu.fleet.loadgen import bench_fleet

        fleet_row = bench_fleet()
    except Exception as e:
        fleet_row = {"fleet_error": repr(e)[:500]}
    # Scaling-efficiency gate rows (the MULTICHIP_r0*.json series made
    # self-policing): per-chip train throughput at every power-of-two
    # mesh shape this session's devices allow, plus the cross-host
    # data-wait spread of a 2-process CPU probe run. Either half failing
    # degrades to absent gate keys with the error in-artifact — the
    # main headline numbers are already paid for.
    scaling_rows: dict = {}
    try:
        sc = measure_scaling(cfg, repeats=2)
        for n, row in sc["shapes"].items():
            scaling_rows[f"scaling_sps_per_chip_{n}x"] = (
                row["samples_per_sec_per_chip"]
            )
        if "scaling_efficiency" in sc:
            scaling_rows["scaling_efficiency"] = sc["scaling_efficiency"]
    except Exception as e:
        scaling_rows["scaling_error"] = repr(e)[:500]
    try:
        scaling_rows["data_wait_spread"] = (
            measure_host_spread()["data_wait_spread"]
        )
    except Exception as e:
        scaling_rows["spread_probe_error"] = repr(e)[:500]
    e2e = {}
    if os.path.isdir(E2E_CACHE):
        import tempfile

        from featurenet_tpu import obs
        from featurenet_tpu.obs import windows as obs_windows

        kw = dict(data_cache=E2E_CACHE, data_workers=1,
                  checkpoint_dir=None, heartbeat_file=None)
        # e2e rows measure the FLAGSHIP arch (round-4 verdict: the artifact's
        # headline arch had no end-to-end number of record); one warp64
        # HBM row rides along for cross-round comparability with the
        # round-3/4 wall-clock study in BASELINE.md.
        # The plain row doubles as the live-SLO capture: an ambient run
        # dir + window aggregator ride the Trainer's own dispatch path
        # (a handful of span emits per dispatch group — no measurable
        # overhead at this cadence) and the resulting data-wait/queue
        # window percentiles land in the gate summary below.
        slo_dir = tempfile.mkdtemp(prefix="bench_slo_")
        obs.init_run(slo_dir, extra={"cmd": "bench_e2e"}, process_index=0)
        obs_windows.install(obs_windows.WindowAggregator())
        try:
            plain = measure_e2e(get_config("sprint64", **kw))
        finally:
            obs.close_run()  # flushes the final window cycle
        slo_fields = _window_gate_fields(slo_dir)
        import shutil

        shutil.rmtree(slo_dir, ignore_errors=True)  # read once, never kept
        piped = measure_e2e(
            get_config("sprint64", steps_per_dispatch=E2E_K, **kw)
        )
        hbm = measure_e2e(
            get_config("sprint64", hbm_cache=True,
                       steps_per_dispatch=E2E_K, **kw),
            steps=96,
        )
        warp_hbm = measure_e2e(
            get_config("warp64", hbm_cache=True,
                       steps_per_dispatch=E2E_K, **kw),
            steps=96,
        )
        e2e = {
            "e2e_arch": "sprint64",
            "e2e_samples_per_sec": plain["e2e_samples_per_sec"],
            "e2e_spread_pct": plain["e2e_spread_pct"],
            "e2e_pipelined_samples_per_sec": piped["e2e_samples_per_sec"],
            "e2e_pipelined_spread_pct": piped["e2e_spread_pct"],
            "e2e_hbm_samples_per_sec": hbm["e2e_samples_per_sec"],
            "e2e_hbm_spread_pct": hbm["e2e_spread_pct"],
            "e2e_steps_per_dispatch": E2E_K,
            "e2e_pipeline_speedup": round(
                piped["e2e_samples_per_sec"]
                / max(plain["e2e_samples_per_sec"], 1e-9), 2
            ),
            # Device-resident dataset + fused dispatch vs the unpipelined
            # host-streamed loop — the round-4 wall-clock headline.
            "e2e_hbm_speedup": round(
                hbm["e2e_samples_per_sec"]
                / max(plain["e2e_samples_per_sec"], 1e-9), 2
            ),
            "e2e_warp64_hbm_samples_per_sec":
                warp_hbm["e2e_samples_per_sec"],
            "e2e_warp64_hbm_spread_pct": warp_hbm["e2e_spread_pct"],
            **slo_fields,
        }
    out = {
        "metric": "featurenet64_train_throughput",
        "backend": platform,
        # Schema 2 (round 5): the SLOPE-TIMED spread fields (spread_pct,
        # serving_spread_pct, warp64/paper_arch spread_pct) are best-two-
        # slope agreement under the shared converged protocol (benchmark.
        # _converged_slope) with *_minmax_pct carrying the full draw
        # range, and slope headlines quote the mean of the two agreeing
        # best draws, not the min. The e2e_*_spread_pct family is a
        # different measurement (whole wall-clock windows through the
        # Trainer's dispatch path, best-of-2) and stays (max-min)/min —
        # see measure_e2e. r01–r03 spread_pct was (max-min)/min over
        # fixed short windows; r04 mixed conventions (serving converged,
        # train fixed-window) under one key — the round-5 advisor finding
        # this field resolves.
        "bench_schema": 2,
        "value": flag["samples_per_sec_per_chip"],
        "unit": "samples/sec/chip",
        "vs_baseline": round(
            flag["samples_per_sec_per_chip"] / V100_SAMPLES_PER_SEC_EST, 3
        ),
        "arch": "sprint64 (5^3 stride-4 s2d stem + 3^3 blocks, batch 256; "
                "held-out 99.98%)",
        "repeats": flag["repeats"],
        "spread_pct": flag["spread_pct"],
        "spread_minmax_pct": flag["spread_minmax_pct"],
        "load_avg_1m": float(os.getloadavg()[0]),
        "load_avg_1m_at_invoke": round(load_at_invoke, 2),
        "gflops_per_sample": flag["gflops_per_sample"],
        "tflops_per_sec_per_chip": flag["tflops_per_sec_per_chip"],
        "mfu": flag["mfu"],
        "mfu_peak_tflops": flag["mfu_peak_tflops"],
        # Performance attribution (obs.perf): MFU restated from the
        # COMPILED programs' own XLA flop counts (vs the analytic `mfu`
        # above), the train executable's peak-memory footprint, and the
        # roofline verdict — present only when the backend answered
        # cost analysis and the device kind has a peak-table entry.
        **{k: flag[k] for k in
           ("mfu_train", "hbm_peak_train_bytes", "train_roofline")
           if k in flag},
        # The bf16-master training row (same arch/batch/protocol as the
        # fp32 headline above; `vs_fp32` is the rung's measured payoff).
        "train_sps_bf16_master": bf16["samples_per_sec_per_chip"],
        "train_bf16_master_spread_pct": bf16["spread_pct"],
        "train_bf16_master_vs_fp32": round(
            bf16["samples_per_sec_per_chip"]
            / max(flag["samples_per_sec_per_chip"], 1e-9), 3
        ),
        **{f"{k}_bf16_master": bf16[k] for k in
           ("mfu_train", "hbm_peak_train_bytes", "train_roofline")
           if k in bf16},
        # The fp16+loss-scaling training row (same arch/batch/protocol;
        # the third train_precision rung — vs_fp32 is its payoff).
        "train_sps_fp16_scaled": fp16["samples_per_sec_per_chip"],
        "train_fp16_scaled_spread_pct": fp16["spread_pct"],
        "train_fp16_scaled_vs_fp32": round(
            fp16["samples_per_sec_per_chip"]
            / max(flag["samples_per_sec_per_chip"], 1e-9), 3
        ),
        **{f"{k}_fp16_scaled": fp16[k] for k in
           ("mfu_train", "hbm_peak_train_bytes", "train_roofline")
           if k in fp16},
        # The layout-specialized 3^3 conv stem row (ops/conv33.py):
        # the flagship under conv_backend=fused33, vs the XLA lowering.
        "train_sps_fused33": fused33["samples_per_sec_per_chip"],
        "train_fused33_spread_pct": fused33["spread_pct"],
        "train_fused33_vs_xla": round(
            fused33["samples_per_sec_per_chip"]
            / max(flag["samples_per_sec_per_chip"], 1e-9), 3
        ),
        **({"serve_mfu": serving["serve_mfu"]}
           if "serve_mfu" in serving else {}),
        "serving_inferences_per_sec_per_chip":
            serving["inferences_per_sec_per_chip"],
        # Best-two-slope agreement after convergence (see measure_inference);
        # serving_spread_minmax_pct is the full draw range incl. outliers.
        "serving_spread_pct": serving["spread_pct"],
        "serving_spread_minmax_pct": serving["spread_minmax_pct"],
        "serving_repeats": serving["repeats"],
        # bf16 serving rung (serve_packed_bf16): throughput, spread, the
        # payoff ratio, and its own measured-cost MFU (serve_mfu_bf16 —
        # the ladder's "did the cast buy bandwidth" evidence).
        "serving_bf16_inferences_per_sec_per_chip":
            serving_bf16["inferences_per_sec_per_chip"],
        "serving_bf16_spread_pct": serving_bf16["spread_pct"],
        "serving_bf16_vs_fp32": round(
            serving_bf16["inferences_per_sec_per_chip"]
            / max(serving["inferences_per_sec_per_chip"], 1e-9), 2
        ),
        **({"serve_mfu_bf16": serving_bf16["serve_mfu"]}
           if "serve_mfu" in serving_bf16 else {}),
        "serving_int8_inferences_per_sec_per_chip":
            serving_int8["inferences_per_sec_per_chip"],
        "serving_int8_spread_pct": serving_int8["spread_pct"],
        "serving_int8_vs_fp32": round(
            serving_int8["inferences_per_sec_per_chip"]
            / max(serving["inferences_per_sec_per_chip"], 1e-9), 2
        ),
        # Warm-start time-to-first-step via the persistent AOT executable
        # cache (runtime registry; serve_packed program).
        "ttfs_cold_s": ttfs["ttfs_cold_s"],
        "ttfs_warm_s": ttfs["ttfs_warm_s"],
        "ttfs_speedup": ttfs["ttfs_speedup"],
        "ttfs_warm_source": ttfs["warm_source"],
        "warp64_sps_per_chip": warp["samples_per_sec_per_chip"],
        "warp64_spread_pct": warp["spread_pct"],
        "paper_arch_sps_per_chip": paper["samples_per_sec_per_chip"],
        "paper_arch_vs_baseline": round(
            paper["samples_per_sec_per_chip"] / V100_SAMPLES_PER_SEC_EST, 3
        ),
        "paper_arch_mfu": paper["mfu"],
        "paper_arch_spread_pct": paper["spread_pct"],
        # Open-loop serving row (serve.loadgen.bench_serving): sustained
        # QPS, end-to-end p50/p99 at the target load (server- AND
        # client-observed), mean batch occupancy of the bucket ladder,
        # overload rejections.
        **serve_row,
        **trace_row,
        # Model-quality telemetry tax row (serve.loadgen.
        # measure_quality_overhead): the quality plane's hot-path cost,
        # pinned max like trace_overhead_pct.
        **quality_row,
        # Incident-plane tax row (serve.loadgen.
        # measure_incident_overhead): the cost of an ARMED incident
        # manager on the emit path, pinned max like trace_overhead_pct.
        **incident_row,
        # Fleet robustness row (fleet.loadgen.bench_fleet): router-level
        # sustained QPS / p99 through a mid-run replica kill, dropped
        # admitted requests (pinned 0), spillover/re-submit counts.
        **fleet_row,
        **scaling_rows,
        **e2e,
    }
    # Self-policing (obs.gates): every round carries a pin-ready
    # gate_summary, and — when a previous round pinned BENCH_baseline.json
    # — judges itself against it in-artifact ("gate": {"ok": ...,
    # "failed": [...]}). The pin then refreshes to this round, so the gate
    # always compares consecutive rounds. Exit code stays 0 on a gate
    # fail: the artifact is the record (a non-zero exit would read as an
    # outage and hide the very numbers that show the regression).
    values = obs_gates.bench_gate_values(out)
    out["gate_summary"] = obs_gates.make_baseline(
        values, tolerance=GATE_TOLERANCE
    )
    # Spread pins bound measurement quality, not performance; give them
    # the absolute slack (see SPREAD_TOLERANCE_ABS) so honest noisy
    # rounds pass while a blown-up spread still fails the self-check.
    # The window pins sit near ZERO by design on a healthy pipeline
    # (a well-fed consumer barely waits), where a relative tolerance
    # pins "never change" — give them absolute room too: the gate is
    # for a starving round (p99 jumping by milliseconds, depth
    # collapsing past a whole slot), not sub-ms wiggle.
    # The TTFS pins get absolute slack too: compile time jitters with host
    # load (seconds-scale), and a warm start that degraded to a fresh
    # compile (probe reject) should fail the pin by the COLD margin, not
    # by sub-second wiggle.
    # The serve latency pins get absolute room like the window pins: at a
    # healthy load p50 sits near the flush deadline (single-digit ms)
    # where relative tolerance pins "never change"; serve_rejected's
    # baseline is 0 by design, so only absolute slack is meaningful.
    # (fleet_requests_dropped deliberately has NO slack entry: its
    # baseline is 0 and any drop is a real regression of the fleet's
    # central promise.)
    obs_gates.apply_abs_slack(out["gate_summary"])
    if os.path.exists(GATE_BASELINE):
        try:
            out["gate"] = obs_gates.evaluate_gates(
                values, obs_gates.load_baseline(GATE_BASELINE)
            )
        except (OSError, ValueError, TypeError, KeyError) as e:
            # A corrupt/hand-mangled pin must degrade the GATE, never the
            # round: the measurements above are already paid for, and the
            # pin refresh below replaces the broken file.
            out["gate"] = {"ok": False, "error": repr(e)[:500]}
    with open(GATE_BASELINE, "w") as fh:
        json.dump(out["gate_summary"], fh, indent=1)
    return out


if __name__ == "__main__":
    main()
